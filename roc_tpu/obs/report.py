"""Render a -obs run's trace + metrics into a text summary, plus the
preflight selftest.

`python -m roc_tpu.obs report -dir roc_obs` reads the two artifacts a
`-obs` run writes (trace.json, metrics.jsonl) and prints per-span-type
aggregates, the epoch/loss trajectory, and any watchdog alerts — the
10-second answer to "where did this run spend its time" without opening
Perfetto.  `selftest` is the preflight/CI gate: tracer schema validity,
watchdog fire/quiet behavior, and the span overhead bound, all stdlib-only
(no jax import) so it runs in ~100 ms.
"""

from __future__ import annotations

import json
from typing import List

from roc_tpu.obs.metrics import load_jsonl
from roc_tpu.obs.tracer import SpanTracer, validate_chrome_trace
from roc_tpu.obs.watchdog import PerfWatchdog

# Gates for the selftest's overhead check.  A disabled span is two
# perf_counter_ns calls + a list push/pop; an enabled one adds a ring
# append.  50 us/span is ~100x the measured cost — the gate catches a
# pathological regression (lock contention, accidental I/O), not jitter.
MAX_SPAN_OVERHEAD_S = 50e-6


def summarize_trace(trace: dict) -> List[str]:
    by_name: dict = {}
    for ev in trace.get("traceEvents", []):
        st = by_name.setdefault(ev.get("name", "?"),
                                {"count": 0, "total_us": 0.0, "max_us": 0.0})
        st["count"] += 1
        dur = float(ev.get("dur", 0.0))
        st["total_us"] += dur
        st["max_us"] = max(st["max_us"], dur)
    lines = [f"# spans ({len(by_name)} types)"]
    for name, st in sorted(by_name.items(), key=lambda kv: -kv[1]["total_us"]):
        mean = st["total_us"] / st["count"]
        lines.append(f"#   {name:<16} x{st['count']:<5} "
                     f"total {st['total_us'] / 1e3:9.2f} ms  "
                     f"mean {mean / 1e3:8.3f} ms  "
                     f"max {st['max_us'] / 1e3:8.3f} ms")
    return lines


def _alert_detail(a: dict) -> str:
    """Generic one-line rendering of a watchdog alert's numeric fields —
    no per-kind template, so a new alert kind (stream-stall,
    calibration-drift, whatever comes next) renders correctly instead of
    falling into a slow-epoch-shaped else branch."""
    parts = []
    for k in sorted(a):
        v = a[k]
        if k in ("kind", "epoch") or isinstance(v, bool) \
                or not isinstance(v, (int, float)):
            continue
        parts.append(f"{k}={v:.4g}")
    return ", ".join(parts)


def summarize_metrics(records: List[dict]) -> List[str]:
    epochs = [r for r in records if r.get("type") == "metrics"]
    alerts = [r for r in records if r.get("type") == "watchdog"]
    trains = [r for r in records if r.get("type") == "train"]
    lines: List[str] = []
    # record-type census first, fully generic: every "type" in the stream
    # counts, including kinds this renderer knows nothing about
    by_type: dict = {}
    for r in records:
        t = str(r.get("type", "?"))
        by_type[t] = by_type.get(t, 0) + 1
    if by_type:
        lines.append("# records: " + ", ".join(
            f"{t} x{n}" for t, n in sorted(by_type.items())))
    if epochs:
        walls = [r["wall_s"] for r in epochs if "wall_s" in r]
        med = sorted(walls)[len(walls) // 2] if walls else 0.0
        lines.append(f"# metrics: {len(epochs)} epochs, "
                     f"median {med * 1e3:.1f} ms/epoch")
        last = epochs[-1]
        for key in ("loss", "grad_norm", "param_norm", "wire_bytes",
                    "mfu", "roofline_frac"):
            if key in last:
                lines.append(f"#   final {key} = {last[key]:.6g}")
    for r in trains:
        lines.append(f"#   verdict: {r.get('watchdog_verdict', '?')} "
                     f"({r.get('epochs', '?')} epochs, "
                     f"total {r.get('total_s', 0):.2f}s)")
    if alerts:
        lines.append(f"# watchdog alerts ({len(alerts)}):")
        for a in alerts:
            lines.append(f"#   {a.get('kind', '?')} @ epoch "
                         f"{a.get('epoch', '?')}: {_alert_detail(a)}")
    elif epochs or trains:
        lines.append("# watchdog: no alerts")
    if any(r.get("type") in ("prediction", "measurement") for r in records):
        lines.extend(summarize_calibration(records))
    return lines


def summarize_calibration(records: List[dict]) -> List[str]:
    """Per-cost-model calibration table over a stream's ledger records
    (the body of `python -m roc_tpu.obs calibration`)."""
    from roc_tpu.obs.ledger import calibration_report, validate_records
    problems = validate_records(records)
    rep = calibration_report(records)
    lines = [f"# calibration: {len(rep['models'])} paired model(s), "
             f"{rep['predictions']} predictions "
             f"({rep['unpaired_predictions']} unpaired), "
             f"{rep['unpaired_measurements']} unpaired measurement(s)"]
    for name in sorted(rep["models"]):
        m = rep["models"][name]
        lines.append(f"#   {name:<14} x{m['pairs']:<4} "
                     f"ratio mean {m['ratio_mean']:.4g}  "
                     f"[{m['ratio_min']:.4g}, {m['ratio_max']:.4g}]  "
                     f"({m['units']})")
    if problems:
        lines.append(f"# calibration: {len(problems)} schema problem(s): "
                     f"{problems[0]}")
    return lines


def report(trace_path: str = "", metrics_path: str = "") -> str:
    lines: List[str] = []
    if trace_path:
        try:
            with open(trace_path, encoding="utf-8") as f:
                trace = json.load(f)
        except (OSError, ValueError) as e:
            lines.append(f"# trace: unreadable ({e})")
        else:
            problems = validate_chrome_trace(trace)
            if problems:
                lines.append(f"# trace: {len(problems)} schema problem(s): "
                             f"{problems[0]}")
            lines.extend(summarize_trace(trace))
    if metrics_path:
        records = load_jsonl(metrics_path)
        if records:
            lines.extend(summarize_metrics(records))
        else:
            lines.append(f"# metrics: no records at {metrics_path}")
    return "\n".join(lines) if lines else "# nothing to report"


# -- calibration (the ledger's CLI + preflight gate) -----------------------

CALIB_MIN_MODELS = 5
# Sanity bands (measured/predicted mean ratio) for the models a CPU run
# can actually check.  The step-count predictors are exact by
# construction; the byte analytics get float32-channel + approximation
# slack; overlap_frac just has to be a sane fraction.  step_time is
# deliberately absent — its constants are TPU-fit, so a CPU ratio is
# reported but never judged (same rule the watchdog applies).
CALIB_BOUNDS = {
    "plan_steps": (0.999, 1.001),
    "staging_rows": (0.999, 1.001),
    "wire_bytes": (0.99, 1.01),
    "overlap_frac": (0.02, 1.5),
    "arg_bytes": (0.9, 1.1),
}


def calibration(metrics_path: str, out=print) -> int:
    """`python -m roc_tpu.obs calibration`: join and report a stream's
    ledger records.  0 = schema-valid records found, 1 = schema problems,
    2 = no ledger records at all."""
    records = load_jsonl(metrics_path)
    if not any(r.get("type") in ("prediction", "measurement")
               for r in records):
        out(f"# no ledger records at {metrics_path!r} "
            "(run with -obs / ROC_OBS=1 first)")
        return 2
    from roc_tpu.obs.ledger import validate_records
    for line in summarize_calibration(records):
        out(line)
    return 1 if validate_records(records) else 0


def calibration_selftest(out=print) -> int:
    """Preflight calibration gate: a 3-epoch CPU run (in-core + streamed)
    plus a binned plan build and an XLA buffer cross-check must produce
    paired records for >= CALIB_MIN_MODELS distinct cost models, the
    stream must validate against the record schema, and every
    CPU-checkable model's mean ratio must sit inside CALIB_BOUNDS."""
    import os
    import tempfile

    import numpy as np

    from roc_tpu.graph import datasets
    from roc_tpu.models import build_gcn
    from roc_tpu.obs.ledger import (calibration_report, get_ledger,
                                    validate_records)
    from roc_tpu.obs.metrics import MetricsRegistry
    from roc_tpu.train.config import Config
    from roc_tpu.train.driver import Trainer

    failures: List[str] = []
    quiet = lambda *a, **k: None  # noqa: E731
    with tempfile.TemporaryDirectory(prefix="roc_calib_") as td:
        jsonl = os.path.join(td, "metrics.jsonl")
        ds = datasets.synthetic("calib", 120, 4.0, 8, 3, n_train=30,
                                n_val=30, n_test=30, seed=7)
        # (a) in-core trainer: step_time / peak-memory predictions, epoch
        # wall measurements — the normal -obs wiring end to end
        cfg = Config(layers=[8, 8, 3], num_epochs=3, eval_every=1000,
                     dropout_rate=0.0, obs=True, obs_dir=td)
        tr = Trainer(cfg, ds, build_gcn(cfg.layers, 0.0))
        tr.train(print_fn=quiet)
        # (b) stream executor: overlap_frac + host-wire byte pairs
        from roc_tpu.stream.executor import StreamTrainer
        scfg = Config(layers=[8, 8, 3], num_epochs=3, num_parts=2,
                      stream=True, stream_slots=2, eval_every=1000,
                      dropout_rate=0.0, obs=True, obs_dir=td)
        st = StreamTrainer(scfg, ds, build_gcn(scfg.layers, 0.0))
        st.train(print_fn=quiet)
        # (c) binned schedule: choose_geometry predicts, the built plan
        # measures (exact-by-construction pairs)
        led = get_ledger()
        reg = MetricsRegistry(jsonl_path=jsonl)
        led.attach(reg.emit)
        from roc_tpu.ops.pallas import binned as B
        rng = np.random.default_rng(0)
        E, N = 4000, 512
        src = rng.integers(0, N, E).astype(np.int64)
        dst = rng.integers(0, N, E).astype(np.int64)
        geom, _ = B.choose_geometry(src, dst, N, N, force=True)
        if geom is not None and geom.hub_minc == 0:
            B.build_binned_plan(src, dst, N, N, geom=geom)
        else:  # hybrid winner: pin a plain preset so the pair still joins
            geom, _ = B.choose_geometry(src, dst, N, N, force=True,
                                        candidates=[B.GEOM_FLAT])
            B.build_binned_plan(src, dst, N, N, geom=geom)
        # (d/e) XLA cross-checks where the backend implements
        # memory_analysis: analytic argument bytes and the planner's peak
        # against the compiled step's own buffer accounting
        from roc_tpu import memory
        stats = memory.xla_memory_stats(tr)
        if stats.get("argument_bytes"):
            led.predict("arg_bytes", "selftest", memory.step_arg_bytes(tr),
                        "bytes")
            led.measure("arg_bytes", "selftest",
                        stats["argument_bytes"] + stats.get("alias_bytes", 0),
                        "bytes")
            led.predict("peak_memory", "selftest-xla",
                        tr.mem_plan.predicted_peak_bytes, "bytes")
            led.measure("peak_memory", "selftest-xla",
                        stats["argument_bytes"] + stats.get("output_bytes", 0)
                        + stats.get("temp_bytes", 0), "bytes")
        led.detach()
        records = load_jsonl(jsonl)

    problems = validate_records(records)
    if problems:
        failures.append(f"{len(problems)} schema problem(s): {problems[0]}")
    rep = calibration_report(records)
    models = rep["models"]
    if len(models) < CALIB_MIN_MODELS:
        failures.append(f"only {len(models)} paired cost model(s) "
                        f"({sorted(models)}), need {CALIB_MIN_MODELS}")
    for name, (lo, hi) in CALIB_BOUNDS.items():
        m = models.get(name)
        if m and not (lo <= m["ratio_mean"] <= hi):
            failures.append(f"{name} mean ratio {m['ratio_mean']:.4g} "
                            f"outside [{lo}, {hi}]")
    if failures:
        for f_ in failures:
            out(f"calibration selftest FAIL: {f_}")
        return 1
    out(f"calibration selftest ok ({len(models)} paired models: "
        + ", ".join(f"{n} @ {models[n]['ratio_mean']:.3g}"
                    for n in sorted(models)) + ")")
    return 0


# -- selftest (the preflight obs gate) -------------------------------------

def selftest(out=print) -> int:
    """0 when the obs layer holds its own contracts; 1 with a reason."""
    failures: List[str] = []

    # 1. tracer: nesting depths + Perfetto-loadable export
    tr = SpanTracer(capacity=64)
    tr.enabled = True
    with tr.span("outer", case="selftest"):
        with tr.span("inner"):
            pass
    spans = {s.name: s for s in tr.spans()}
    if set(spans) != {"outer", "inner"}:
        failures.append(f"tracer recorded {sorted(spans)}, "
                        "expected inner+outer")
    elif not (spans["inner"].depth == 1 and spans["outer"].depth == 0):
        failures.append("span nesting depths wrong")
    problems = validate_chrome_trace(tr.to_chrome_trace())
    if problems:
        failures.append(f"chrome-trace schema: {problems[0]}")
    try:
        json.dumps(tr.to_chrome_trace())
    except TypeError as e:
        failures.append(f"trace not JSON-serializable: {e}")

    # 2. watchdog: fires on an injected 3x epoch, quiet on a clean run
    wd = PerfWatchdog()
    for epoch in range(5):
        if wd.observe_epoch(epoch, 0.1) is not None:
            failures.append("watchdog fired on a clean warmup")
            break
    if wd.observe_epoch(5, 0.3) is None:
        failures.append("watchdog missed an injected 3x slow epoch")
    clean = PerfWatchdog()
    noise = [0.1, 0.102, 0.098, 0.101, 0.099, 0.103, 0.097]
    if any(clean.observe_epoch(i, t) for i, t in enumerate(noise)):
        failures.append("watchdog fired on +-3% noise")
    if not clean.observe_shards(0, [0.1, 0.1, 0.1, 0.5]):
        failures.append("watchdog missed a 5x shard straggler")

    # 3. overhead: disabled spans (the always-on steady state) stay cheap
    tr2 = SpanTracer()
    reps = 2000
    with tr2.span("gate") as gate:   # obs times itself — no raw clocks
        for _ in range(reps):
            with tr2.span("probe"):
                pass
    per_span = gate.dur_s / reps
    if per_span > MAX_SPAN_OVERHEAD_S:
        failures.append(f"span overhead {per_span * 1e6:.1f} us > "
                        f"{MAX_SPAN_OVERHEAD_S * 1e6:.0f} us")

    if failures:
        for f_ in failures:
            out(f"obs selftest FAIL: {f_}")
        return 1
    out(f"obs selftest ok (span overhead {per_span * 1e6:.2f} us, "
        f"watchdog fire/quiet verified, trace schema valid)")
    return 0
