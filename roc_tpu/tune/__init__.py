"""Geometry autotuner (round 12): search the kernel-config space, persist
winners, refit the cost-model constants.

The binned kernels' Geometry constants were hand-picked from a handful of
hardware points (docs/PERF.md rounds 2-5); `choose_geometry` ranks ~10
hand-written presets through an analytic cost model.  This package turns
that into a measured SEARCH:

  lattice.py    the candidate space — every Geometry the invariants and
                the VMEM budget admit (chunk widths, slot, windows, group
                target, flat/unit) crossed with the non-Geometry kernel
                knobs (_DMA_CLS run classes, dimension_semantics,
                double-buffer depth, mega on/off).
  surrogate.py  trial pricing: a parameterized mirror of binned's
                analytic model (exact _plan_steps schedules), plus the
                seeded CI surrogate — deterministic pseudo-measurements
                so the whole loop runs on CPU — and the device timing
                path for hardware windows.
  search.py     successive halving: analytic screen of the full lattice
                -> short trials -> confirmation of finalists, every trial
                paired through the calibration ledger (obs/ledger.py).
  store.py      the content-keyed ``tuned.json`` tier `choose_geometry`
                consults BEFORE its analytic model — same key discipline
                as the ROC_PLAN_CACHE plan cache, stored alongside it.
  refit.py      re-solve _CHUNK_OVERHEAD_S, the flat staging-DMA term,
                and the matmul per-chunk rate from trial records; on
                device, emit the kernel_budgets.json measured table.

Entry points: ``python -m roc_tpu.tune`` (see __main__.py), the driver's
``-autotune`` / ``ROC_AUTOTUNE=1`` flag, and hw_revalidate step 3h.
Determinism contract: the surrogate sweep is bit-reproducible (seeded
hashlib noise, sorted iteration, no wall clocks), so CI pins
byte-identical tuned.json across runs; device tables keep the
measured_calibration refusal contract (interpret timings never persist
as rates).
"""

from roc_tpu.tune.lattice import KernelConfig, candidate_lattice  # noqa: F401
from roc_tpu.tune.search import autotune_graph, sweep  # noqa: F401
from roc_tpu.tune.store import (  # noqa: F401
    graph_key, load_store, lookup, save_store, tuned_store_path,
    validate_store, variant_key)
