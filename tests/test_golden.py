"""Golden accuracy curves (docs/GOLDEN.md): fixed-seed end-to-end training
must reproduce the recorded curve within cross-platform float tolerance.
This is the framework's version of the reference's de-facto oracle
(SURVEY §4: correctness regression == accuracy divergence)."""

import jax
import pytest

from roc_tpu.graph import datasets
from roc_tpu.models import build_gcn
from roc_tpu.train.config import Config
from roc_tpu.train.driver import Trainer


def _run(name, layers, wd, epochs, seed=1):
    ds = datasets.get(name, seed=seed)
    cfg = Config(layers=layers, num_epochs=epochs, learning_rate=0.01,
                 weight_decay=wd, dropout_rate=0.5, seed=seed,
                 eval_every=10**9)
    tr = Trainer(cfg, ds, build_gcn(layers, cfg.dropout_rate))
    curve = {}
    for epoch in range(epochs + 1):
        if epoch in (5, 10, 20):
            curve[epoch] = jax.device_get(tr.evaluate())
        if epoch < epochs:
            tr.run_epoch()
    return curve


@pytest.mark.slow
def test_golden_cora_curve():
    curve = _run("cora", [1433, 16, 7], 5e-4, 20)
    # GOLDEN.md: 96.40 / 98.20 / 97.80 @ epochs 5/10/20 (loss 0.67 @ 20)
    assert curve[5].val_correct / curve[5].val_all >= 0.94
    assert curve[20].val_correct / curve[20].val_all >= 0.965
    assert float(curve[20].train_loss) <= 1.5


@pytest.mark.slow
def test_golden_reddit_small_curve():
    curve = _run("reddit-small", [602, 128, 41], 1e-4, 10)
    # GOLDEN.md: saturates by epoch 5; epoch-10 pin with headroom
    assert curve[10].val_correct / curve[10].val_all >= 0.995
    assert float(curve[10].train_loss) <= 1.0


@pytest.mark.slow
def test_golden_cora_curve_binned_backend():
    """The binned backend's designed bf16 rounding must not move the golden
    curve (docs/GOLDEN.md records the full metric lines: accuracy counts
    agree with fp32 to within +-1 sample at every checkpoint)."""
    ds = datasets.get("cora", seed=1)
    cfg = Config(layers=[1433, 16, 7], num_epochs=20, learning_rate=0.01,
                 weight_decay=5e-4, dropout_rate=0.5, seed=1,
                 eval_every=10**9, aggregate_backend="binned")
    tr = Trainer(cfg, ds, build_gcn(cfg.layers, cfg.dropout_rate))
    for _ in range(20):
        tr.run_epoch()
    m = jax.device_get(tr.evaluate())
    assert m.val_correct / m.val_all >= 0.965
    assert float(m.train_loss) <= 1.5


@pytest.mark.slow
@pytest.mark.parametrize("name,pins", [
    # (epoch, min val accuracy); final (epoch, max loss) — docs/GOLDEN.md
    ("sage", {5: 0.96, 20: 0.975, "loss20": 0.1}),
    ("gin", {20: 0.78, "loss20": 33.0}),
    ("gat", {20: 0.955, "loss20": 0.5}),
])
def test_golden_zoo_curves(name, pins):
    """Fixed-seed accuracy pins for the model zoo (docs/GOLDEN.md) — the
    zoo's version of the reference's accuracy oracle.  Conservative
    thresholds leave cross-platform float headroom."""
    from roc_tpu.models import build_model

    ds = datasets.get("cora", seed=1)
    cfg = Config(layers=[1433, 16, 7], num_epochs=20, learning_rate=0.01,
                 weight_decay=5e-4, dropout_rate=0.5, seed=1,
                 eval_every=10**9)
    tr = Trainer(cfg, ds, build_model(name, cfg.layers, cfg.dropout_rate))
    for epoch in range(20):
        if epoch in pins:
            m = jax.device_get(tr.evaluate())
            assert m.val_correct / m.val_all >= pins[epoch], (name, epoch)
        tr.run_epoch()
    m = jax.device_get(tr.evaluate())
    if 20 in pins:
        assert m.val_correct / m.val_all >= pins[20], name
    assert float(m.train_loss) <= pins["loss20"], name
