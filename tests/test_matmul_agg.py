"""Scatter-free matmul aggregation backend tests.

Same oracle strategy as the Pallas kernel tests (SURVEY.md §7.3): dense
NumPy aggregation for forward, explicit Aᵀ for the VJP, and end-to-end
training equality against the XLA segment_sum backend, single-device and
sharded.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from roc_tpu import ops
from roc_tpu.graph import datasets
from roc_tpu.models import build_gcn
from roc_tpu.parallel.spmd import SpmdTrainer
from roc_tpu.train.config import Config
from roc_tpu.train.driver import Trainer, resolve_backend


def graph_and_x(seed=3, n=150, h=16):
    ds = datasets.synthetic("t", n, 4.0, 8, 4, n_train=20, n_val=20,
                            n_test=20, seed=seed)
    g = ds.graph
    x = np.random.default_rng(seed).normal(size=(g.num_nodes, h)).astype(
        np.float32)
    return ds, g, x


def dense_agg(g, x):
    out = np.zeros_like(x)
    np.add.at(out, g.dst_idx, x[g.col_idx])
    return out


def test_forward_matches_dense():
    _, g, x = graph_and_x()
    plans = ops.build_aggregate_plans(g.col_idx, g.dst_idx, g.num_nodes,
                                      g.num_nodes)
    out = ops.scatter_gather_matmul(jnp.asarray(x), plans, g.num_nodes,
                                    g.num_nodes)
    np.testing.assert_allclose(np.asarray(out), dense_agg(g, x), rtol=1e-5,
                               atol=1e-5)


def test_forward_multi_step_scan(monkeypatch):
    # Force the production path: several scan steps, pad chunks in the last
    # step, and nonzero dynamic-update-slice bases.
    from roc_tpu.ops import aggregate
    monkeypatch.setattr(aggregate, "_MM_CB", 32)
    _, g, x = graph_and_x(n=600, h=8)
    plans = ops.build_aggregate_plans(g.col_idx, g.dst_idx, g.num_nodes,
                                      g.num_nodes)
    C = plans.fwd_obi.shape[0]
    assert C > 32 and C % 32 != 0, "fixture must span steps + pad chunks"
    out = ops.scatter_gather_matmul(jnp.asarray(x), plans, g.num_nodes,
                                    g.num_nodes)
    np.testing.assert_allclose(np.asarray(out), dense_agg(g, x), rtol=1e-5,
                               atol=1e-5)
    # gradient across step boundaries too
    ct = np.random.default_rng(5).normal(size=x.shape).astype(np.float32)
    grad = jax.grad(lambda x: jnp.sum(ops.scatter_gather_matmul(
        x, plans, g.num_nodes, g.num_nodes) * ct))(jnp.asarray(x))
    a = np.zeros((g.num_nodes, g.num_nodes), np.float32)
    np.add.at(a, (g.dst_idx, g.col_idx), 1.0)
    np.testing.assert_allclose(np.asarray(grad), a.T @ ct, rtol=1e-4,
                               atol=1e-4)


def test_vjp_matches_transposed_aggregation():
    _, g, x = graph_and_x(h=8)
    plans = ops.build_aggregate_plans(g.col_idx, g.dst_idx, g.num_nodes,
                                      g.num_nodes)
    ct = np.random.default_rng(9).normal(size=x.shape).astype(np.float32)

    def f(x):
        return jnp.sum(ops.scatter_gather_matmul(
            x, plans, g.num_nodes, g.num_nodes) * ct)
    grad = jax.grad(f)(jnp.asarray(x))
    a = np.zeros((g.num_nodes, g.num_nodes), np.float32)
    np.add.at(a, (g.dst_idx, g.col_idx), 1.0)
    np.testing.assert_allclose(np.asarray(grad), a.T @ ct, rtol=1e-4,
                               atol=1e-4)


def test_rectangular_table():
    _, g, x = graph_and_x()
    extra = 24
    table = np.concatenate(
        [x, np.random.default_rng(1).normal(size=(extra, x.shape[1]))
         .astype(np.float32)])
    src = g.col_idx.astype(np.int64).copy()
    src[::7] = g.num_nodes + (src[::7] % extra)
    plans = ops.build_aggregate_plans(src, g.dst_idx, g.num_nodes,
                                      table.shape[0])
    out = ops.scatter_gather_matmul(jnp.asarray(table), plans, g.num_nodes,
                                    table.shape[0])
    expect = np.zeros_like(x)
    np.add.at(expect, g.dst_idx, table[src])
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5, atol=1e-5)


def test_training_matmul_equals_xla_single_device():
    ds, g, _ = graph_and_x()
    cfg_x = Config(layers=[ds.in_dim, 8, ds.num_classes], num_epochs=3,
                   dropout_rate=0.0, eval_every=10**9,
                   aggregate_backend="xla")
    cfg_m = Config(layers=[ds.in_dim, 8, ds.num_classes], num_epochs=3,
                   dropout_rate=0.0, eval_every=10**9,
                   aggregate_backend="matmul")
    tx = Trainer(cfg_x, ds, build_gcn(cfg_x.layers, 0.0))
    tm = Trainer(cfg_m, ds, build_gcn(cfg_m.layers, 0.0))
    for i in range(3):
        lx, lm = float(tx.run_epoch()), float(tm.run_epoch())
        np.testing.assert_allclose(lm, lx, rtol=1e-4, err_msg=f"epoch {i}")
    np.testing.assert_allclose(
        np.asarray(tm.params["linear_0"]), np.asarray(tx.params["linear_0"]),
        rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("halo", [False, True])
def test_training_matmul_equals_xla_sharded(halo):
    ds, g, _ = graph_and_x(n=220)
    base = dict(layers=[ds.in_dim, 8, ds.num_classes], num_epochs=2,
                dropout_rate=0.0, eval_every=10**9, num_parts=4, halo=halo)
    tx = SpmdTrainer(Config(**base, aggregate_backend="xla"), ds,
                     build_gcn(base["layers"], 0.0))
    tm = SpmdTrainer(Config(**base, aggregate_backend="matmul"), ds,
                     build_gcn(base["layers"], 0.0))
    for i in range(2):
        lx, lm = float(tx.run_epoch()), float(tm.run_epoch())
        np.testing.assert_allclose(lm, lx, rtol=1e-4, err_msg=f"epoch {i}")


def test_empty_graph():
    x = jnp.ones((10, 8))
    plans = ops.build_aggregate_plans(np.zeros(0, np.int64),
                                      np.zeros(0, np.int64), 10, 10)
    out = ops.scatter_gather_matmul(x, plans, 10, 10)
    np.testing.assert_array_equal(np.asarray(out), np.zeros((10, 8)))


def test_auto_backend_resolution(monkeypatch):
    # "pallas" now aliases the binned two-phase kernel (docs/PERF.md)
    assert resolve_backend("pallas", 100) == "binned"
    # on non-TPU platforms auto always picks xla (native scatter is fine)
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert resolve_backend("auto", 1 << 21) == "xla"
    # on TPU, auto switches by edge count
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert resolve_backend("auto", 100) == "xla"
    assert resolve_backend("auto", 1 << 21) == "matmul"
    # AUTO_BINNED default is True (hardware-measured win, PERF.md): with
    # geometry given and viable, auto resolves to binned
    assert resolve_backend("auto", 23_526_267, 232_965, 232_965) == "binned"


def test_fast_precision_plumbs_through():
    """-aggr-precision fast must reach the matmul backend and keep training
    sane.  NOTE: on the CPU test backend DEFAULT and HIGHEST dot precision
    are both full fp32, so this verifies plumbing, not the bf16 rounding —
    hardware numerics are pinned by tests/test_tpu_hw.py."""
    from roc_tpu.graph import datasets
    from roc_tpu.models import build_gcn
    from roc_tpu.train.config import Config
    from roc_tpu.train.driver import Trainer

    ds = datasets.synthetic("prec", 500, 5.0, 16, 4, n_train=100, n_val=100,
                            n_test=100, seed=9)
    layers = [16, 8, 4]
    losses = {}
    for prec in ("exact", "fast"):
        cfg = Config(layers=layers, num_epochs=2, dropout_rate=0.0,
                     eval_every=10**9, aggregate_backend="matmul",
                     aggregate_precision=prec, seed=5)
        tr = Trainer(cfg, ds, build_gcn(layers, 0.0))
        assert tr.gdata.precision == prec
        losses[prec] = [float(tr.run_epoch()) for _ in range(2)]
    np.testing.assert_allclose(losses["fast"], losses["exact"], rtol=5e-3)

def test_forced_matmul_identical_to_auto(monkeypatch):
    """Round-5 forced-vs-auto anomaly root cause (docs/PERF.md): with auto
    resolving to matmul, the forced `-aggr-backend matmul` trainer lowers
    to a BYTE-IDENTICAL train-step program — the measured 8.5x gap
    (256.2 s vs 30.1 s/epoch at the products shape) was cross-invocation
    harness state, not a program difference.  Pinned so a resolution
    change that introduces a real divergence fails loudly; same-process
    steady-state epoch times must also stay within 1.2x.  The hardware
    reproduction of the A/B is one flag:
      ROC_BENCH_SHAPE=products ROC_BENCH_AB=matmul,auto python bench.py
    """
    import hashlib
    import time

    import roc_tpu.train.driver as drv

    # auto must resolve to matmul on CPU: drop the TPU gate + edge floor,
    # and keep binned out of the race
    monkeypatch.setattr(drv, "AUTO_MATMUL_EDGES", 1)
    monkeypatch.setattr(drv, "AUTO_BINNED", False)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    ds, _, _ = graph_and_x(n=600)
    base = dict(layers=[8, 16, 4], num_epochs=1, dropout_rate=0.0,
                eval_every=10**9)
    tf = Trainer(Config(**base, aggregate_backend="matmul"), ds,
                 build_gcn(base["layers"], 0.0))
    ta = Trainer(Config(**base, aggregate_backend="auto"), ds,
                 build_gcn(base["layers"], 0.0))
    assert tf.gdata.backend == ta.gdata.backend == "matmul"

    def step_text(tr):
        return tr._train_step.lower(
            tr.params, tr.opt_state, tr.x, tr.labels, tr.mask, tr.gdata,
            jax.random.key(0), jnp.float32(0.01), np.float32(1.0)).as_text()

    hf = hashlib.sha1(step_text(tf).encode()).hexdigest()
    ha = hashlib.sha1(step_text(ta).encode()).hexdigest()
    assert hf == ha, "forced and auto-resolved matmul lower differently"

    # steady-state parity, same process (bench.py ROC_BENCH_AB's logic in
    # miniature): median over several post-compile epochs.  The programs
    # are byte-identical (pinned above), so any measured gap is scheduler
    # noise — medians mostly absorb it, but a loaded CI box can still
    # skew one trainer's whole measurement window; re-measure up to 3
    # times and assert the BEST ratio, which is the honest statistic for
    # "these identical programs run at the same speed".
    def median_epoch_s(tr, k=10):
        tr.run_epoch()                       # compile epoch, not measured
        drv.device_sync(tr.params)
        times = []
        for _ in range(k):
            t0 = time.perf_counter()
            drv.device_sync(tr.run_epoch())
            times.append(time.perf_counter() - t0)
        return sorted(times)[k // 2]

    best = np.inf
    for _ in range(3):
        mf, ma = median_epoch_s(tf), median_epoch_s(ta)
        best = min(best, max(mf, ma) / min(mf, ma))
        if best < 1.2:
            break
    assert best < 1.2, (mf, ma, best)
