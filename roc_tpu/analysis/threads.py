"""roc-threads: whole-tree lock-discipline and race analyzer.

ROC inherits data-race freedom from Legion's task model; this
reproduction replaced that with hand-rolled Python threads (serve-queue
worker, background replan, prefetch ring, fleet transports).  This pass
makes that concurrency contract machine-checked, the same bet roc-verify
made for collectives: derive the discipline from the AST, commit it as a
baseline (``threads.json``), and refuse drift.

What it computes (CLI: ``tools/roclint.py --threads``):

* **Inventory** — every ``threading.{Lock,RLock,Condition,Event}``
  attribute (plus module-level locks and ``ThreadPoolExecutor``s), every
  ``Thread(target=...)`` spawn with its daemon flag, storage attribute
  and join/shutdown reachability.
* **Lock-order graph** — lock B acquired while A is held, propagated
  through same-class method calls, resolved attribute calls
  (``self.journal.append``) and imported module functions, with
  constructor-argument unification so a lock passed across classes is
  one node (``ServeEngine._plan_lock`` IS ``DeltaManager._plan_lock``).
  Cycles are ``lock-cycle`` findings (potential deadlocks).
* **Guarded-by facts** — an attribute consistently accessed under lock
  L (>= 3 accesses, at least one store) is inferred guarded-by L; a bare
  *store* from any method not reachable from ``__init__`` (construction
  happens-before publication) is an ``unguarded-attr`` finding.  Bare
  loads are never findings: stats snapshots read racily on purpose.
* **Rules** — ``condvar-wait`` (a ``Condition.wait`` outside a predicate
  loop), ``thread-join`` (a spawned thread/pool no ``close()``/join
  reaches), ``lock-blocking`` (a lock held across a blocking or
  chaos-injectable call: ``fault.point``/``fault.retrying``, fsync,
  ``device_put``, socket sends, ``.join``/``.result``/non-condvar
  ``.wait``), ``witness-name`` (a ``witness.trace`` name that disagrees
  with the attribute it is bound to).

Findings are waivable with ``# roclint: allow(<rule>)`` on the offending
or preceding line — waivers must carry a reason (``tools/roclint.py
--list-waivers`` enforces that).  The committed baseline is exact-diffed
like budgets.json; regenerate deliberate drift with
``tools/roclint.py --update-threads`` and review the diff.

Known precision limits (deliberate, mirroring lint.py's per-file trade):
calls through function-valued attributes (``self._serve_fn``), late
bindings (``self.engine.deltas``) and jit-wrapped closures are not
chased.  Runtime orders those paths create are covered by the *witness*
(:mod:`roc_tpu.analysis.witness`): tier-1 arms it around the threaded
suites and validates every real acquisition order against this graph.
Edges real at runtime but invisible to the AST are declared in
``DECLARED_EDGES`` with a reason and become part of the graph.

``python -m roc_tpu.analysis.threads --selftest`` proves the rules bite:
a clean fixture stays clean and each seeded mutation (lock inversion,
dropped guard, waitless condvar wait, unjoined thread) is caught —
test_analysis.py's exchange-flip pattern applied to concurrency.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, List, Optional, Set, Tuple

from roc_tpu.analysis.lint import Finding, _WAIVER_RE, _call_head, _dotted

__all__ = ["analyze_paths", "analyze_source", "load_baseline",
           "diff_baseline", "report_dict", "save_baseline", "selftest",
           "BASELINE_PATH", "DECLARED_EDGES"]

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "threads.json")

# Real runtime lock orders the AST cannot see (calls through
# function-valued attributes); each carries its reason into threads.json
# and the witness validator accepts them like any derived edge.
DECLARED_EDGES: List[Tuple[str, str, str]] = [
    ("ServeEngine._plan_lock", "PrefetchRing._lock",
     "streamed serving: the serve worker holds the plan lock for the "
     "whole window while bundle.predict_logits() sweeps shards through "
     "the prefetch ring (reached through FrozenBundle's stream trainer, "
     "a function-valued attribute outside the static call graph)"),
]

_LOCK_CTORS = {
    "threading.Lock": "Lock", "threading.RLock": "RLock",
    "threading.Condition": "Condition", "threading.Event": "Event",
    "threading.Semaphore": "Semaphore",
    "threading.BoundedSemaphore": "Semaphore",
}
# Lock kinds that guard (Events only gate; they are inventoried but
# never treated as mutual exclusion).
_GUARDING = {"Lock", "RLock", "Condition", "Semaphore", "external"}

# Call heads that block or sit in a chaos kill window; holding a lock
# across one stalls (or strands, under an injected kill) every waiter.
_BLOCKING_HEADS = {
    "fault.point": "fault.point", "fault.retrying": "fault.retrying",
    "fault.fsync_replace": "fsync_replace", "os.fsync": "os.fsync",
    "time.sleep": "time.sleep", "jax.device_put": "device_put",
    "device_put": "device_put",
}
# Attribute calls that block regardless of receiver type.
_BLOCKING_ATTRS = {"join": ".join", "result": ".result",
                   "sendall": ".sendall"}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


# -- inventory dataclasses ---------------------------------------------------

@dataclasses.dataclass
class LockNode:
    name: str            # "DeltaManager._mu" / "fault.inject._LOCK"
    kind: str            # Lock | RLock | Condition | Event | ... | external
    path: str
    line: int
    witness_name: Optional[str] = None   # the trace() string, if wrapped


@dataclasses.dataclass
class ThreadSpawn:
    target: str          # "MicrobatchQueue._run" or "?"
    daemon: bool
    stored: str          # "DeltaManager._replan_thread" / "<local>" / ""
    joined: bool
    pool: bool
    path: str
    line: int


@dataclasses.dataclass
class Report:
    locks: List[LockNode]
    threads: List[ThreadSpawn]
    edges: Dict[Tuple[str, str], Tuple[str, int]]   # (a,b) -> first site
    guarded_by: Dict[str, str]                      # "Class.attr" -> lock
    findings: List[Finding]
    waived: int


# -- phase 1: per-module scan ------------------------------------------------

class _ClassScan:
    def __init__(self, name: str, module: str, path: str,
                 node: ast.ClassDef):
        self.name = name
        self.module = module
        self.path = path
        self.node = node
        self.methods: Dict[str, ast.AST] = {}
        # attr -> (kind, line, witness_name)
        self.locks: Dict[str, Tuple[str, int, Optional[str]]] = {}
        # attr -> (param, line): assigned from a ctor parameter
        self.ext_candidates: Dict[str, Tuple[str, int]] = {}
        self.attr_type_heads: Dict[str, str] = {}   # attr -> raw call head
        self.spawns: List[dict] = []
        self.joined_attrs: Set[str] = set()
        self.shutdown_attrs: Set[str] = set()
        self.with_attrs: Set[str] = set()   # self.X used as `with`/.wait


class _ModuleScan:
    def __init__(self, path: str, module: str, tree: ast.Module,
                 src_lines: List[str]):
        self.path = path
        self.module = module
        self.tree = tree
        self.src_lines = src_lines
        self.classes: Dict[str, _ClassScan] = {}
        self.functions: Dict[str, ast.AST] = {}
        self.mod_locks: Dict[str, Tuple[str, int]] = {}   # VAR -> kind, line
        self.aliases: Dict[str, str] = {}   # local name -> dotted module


def _module_name(path: str) -> str:
    p = path.replace(os.sep, "/")
    if p.endswith(".py"):
        p = p[:-3]
    return p.replace("/", ".")


def _unwrap_ifexp(value):
    """`X(...) if flag else None` assigns an X at runtime."""
    while isinstance(value, ast.IfExp):
        value = value.body if isinstance(value.body, ast.Call) \
            else value.orelse
    return value


def _witness_parts(call: ast.Call):
    """(name, inner_ctor_call) for witness.trace("...", threading.X())."""
    head = _call_head(call)
    if not head or head.split(".")[-1] != "trace":
        return None
    if len(call.args) < 2 or not isinstance(call.args[0], ast.Constant) \
            or not isinstance(call.args[0].value, str):
        return None
    inner = call.args[1]
    if isinstance(inner, ast.Call) and _call_head(inner) in _LOCK_CTORS:
        return call.args[0].value, inner
    return None


def _scan_module(path: str, src: str) -> Optional[_ModuleScan]:
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError:
        return None
    ms = _ModuleScan(path, _module_name(path), tree, src.splitlines())
    for node in tree.body:
        if isinstance(node, ast.ImportFrom) and node.module:
            for al in node.names:
                ms.aliases[al.asname or al.name] = \
                    f"{node.module}.{al.name}"
        elif isinstance(node, ast.Import):
            for al in node.names:
                ms.aliases[al.asname or al.name.split(".")[0]] = al.name
        elif isinstance(node, _FUNC_NODES):
            ms.functions[node.name] = node
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            v = _unwrap_ifexp(node.value)
            if isinstance(v, ast.Call) and _call_head(v) in _LOCK_CTORS:
                ms.mod_locks[node.targets[0].id] = (
                    _LOCK_CTORS[_call_head(v)], node.lineno)
        elif isinstance(node, ast.ClassDef):
            ms.classes[node.name] = _scan_class(node, ms)
    return ms


def _self_attr(t) -> Optional[str]:
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
            and t.value.id == "self":
        return t.attr
    return None


def _scan_class(node: ast.ClassDef, ms: _ModuleScan) -> _ClassScan:
    cs = _ClassScan(node.name, ms.module, ms.path, node)
    for item in node.body:
        if isinstance(item, _FUNC_NODES):
            cs.methods[item.name] = item
    for mname, meth in cs.methods.items():
        params = [a.arg for a in meth.args.args[1:]] if meth.args.args \
            else []
        locals_thread: Dict[str, dict] = {}
        for sub in ast.walk(meth):
            if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                targets = sub.targets if isinstance(sub, ast.Assign) \
                    else [sub.target]
                value = _unwrap_ifexp(getattr(sub, "value", None))
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None:
                        # `t = threading.Thread(...)` local spawn
                        if isinstance(t, ast.Name) and \
                                isinstance(value, ast.Call):
                            sp = _spawn_info(value)
                            if sp is not None:
                                sp["local"] = t.id
                                locals_thread[t.id] = sp
                                cs.spawns.append(sp)
                        continue
                    if isinstance(value, ast.Call):
                        wp = _witness_parts(value)
                        if wp is not None:
                            name, inner = wp
                            cs.locks[attr] = (
                                _LOCK_CTORS[_call_head(inner)],
                                value.lineno, name)
                            continue
                        head = _call_head(value)
                        if head in _LOCK_CTORS:
                            cs.locks[attr] = (_LOCK_CTORS[head],
                                              value.lineno, None)
                            continue
                        sp = _spawn_info(value)
                        if sp is not None:
                            sp["stored"] = attr
                            cs.spawns.append(sp)
                            continue
                        if head:
                            cs.attr_type_heads[attr] = head
                    elif isinstance(value, ast.Name):
                        if value.id in params:
                            cs.ext_candidates[attr] = (value.id, sub.lineno)
                        elif value.id in locals_thread:
                            locals_thread[value.id]["stored"] = attr
            elif isinstance(sub, ast.Call):
                h = _dotted(sub.func)
                if h and "." in h:
                    parts = h.split(".")
                    if parts[0] == "self" and len(parts) == 3:
                        if parts[2] == "join":
                            cs.joined_attrs.add(parts[1])
                        elif parts[2] == "shutdown":
                            cs.shutdown_attrs.add(parts[1])
                        elif parts[2] in ("acquire", "wait", "notify",
                                          "notify_all", "wait_for"):
                            cs.with_attrs.add(parts[1])
                    elif len(parts) == 2 and parts[1] == "join" \
                            and parts[0] in locals_thread:
                        locals_thread[parts[0]]["joined_local"] = True
            elif isinstance(sub, ast.With):
                for w in sub.items:
                    d = _dotted(w.context_expr)
                    if d and d.startswith("self.") and d.count(".") == 1:
                        cs.with_attrs.add(d.split(".")[1])
    return cs


def _spawn_info(call: ast.Call) -> Optional[dict]:
    head = _call_head(call)
    if head not in ("threading.Thread", "Thread",
                    "ThreadPoolExecutor",
                    "concurrent.futures.ThreadPoolExecutor"):
        return None
    pool = "Executor" in (head or "")
    target, daemon = "?", False
    for kw in call.keywords:
        if kw.arg == "target":
            target = _dotted(kw.value) or "?"
        elif kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            daemon = bool(kw.value.value)
    return {"target": target, "daemon": daemon, "pool": pool,
            "stored": "", "local": "", "joined_local": False,
            "line": call.lineno}


# -- phase 2: global resolution ---------------------------------------------

class _Tree:
    """Global view over every scanned module."""

    def __init__(self, modules: List[_ModuleScan]):
        self.modules = modules
        self.classes: Dict[str, _ClassScan] = {}
        dup: Set[str] = set()
        for ms in modules:
            for cname, cs in ms.classes.items():
                if cname in self.classes:
                    dup.add(cname)
                else:
                    self.classes[cname] = cs
        self.ambiguous_classes = dup
        self.mod_funcs: Dict[Tuple[str, str], ast.AST] = {}
        for ms in modules:
            for fname, fn in ms.functions.items():
                self.mod_funcs[(ms.module, fname)] = fn

        # confirm external locks (assigned from a ctor param AND used as
        # a lock) and resolve attribute object types
        for cs in self.classes.values():
            for attr, (param, line) in list(cs.ext_candidates.items()):
                if attr in cs.with_attrs and attr not in cs.locks:
                    cs.locks[attr] = ("external", line, None)
            resolved = {}
            for attr, head in cs.attr_type_heads.items():
                last = head.split(".")[-1]
                if last in self.classes and last not in dup:
                    resolved[attr] = last
            cs.attr_types = resolved

        # lock node table + union-find over ctor-passed locks
        self.nodes: Dict[Tuple[str, str], LockNode] = {}
        for cs in self.classes.values():
            for attr, (kind, line, wname) in cs.locks.items():
                self.nodes[(cs.name, attr)] = LockNode(
                    f"{cs.name}.{attr}", kind, cs.path, line, wname)
        for ms in modules:
            for var, (kind, line) in ms.mod_locks.items():
                key = (f"@{ms.module}", var)
                short = ms.module
                for pref in ("roc_tpu.",):
                    if short.startswith(pref):
                        short = short[len(pref):]
                self.nodes[key] = LockNode(f"{short}.{var}", kind,
                                           ms.path, line)
        self._uf: Dict[Tuple[str, str], Tuple[str, str]] = {}

        # unique lock-attr fallback: `mgr._mu` resolves when exactly one
        # class in the tree owns a lock attribute `_mu`
        attr_owner: Dict[str, List[Tuple[str, str]]] = {}
        for (owner, attr) in self.nodes:
            if not owner.startswith("@"):
                attr_owner.setdefault(attr, []).append((owner, attr))
        self.unique_attr = {a: ks[0] for a, ks in attr_owner.items()
                            if len(ks) == 1}

    # union-find ----------------------------------------------------------
    def _find(self, k):
        while k in self._uf:
            k = self._uf[k]
        return k

    def union(self, ext_key, src_key):
        a, b = self._find(ext_key), self._find(src_key)
        if a == b:
            return
        # creation sites win over external nodes as the canonical name
        if self.nodes[a].kind != "external":
            a, b = b, a
        self._uf[a] = b

    def canon(self, key) -> str:
        return self.nodes[self._find(key)].name

    def canon_kind(self, key) -> str:
        return self.nodes[self._find(key)].kind


def _bind_ctor_args(init: ast.AST, call: ast.Call) -> Dict[str, ast.AST]:
    params = [a.arg for a in init.args.args[1:]]
    bound: Dict[str, ast.AST] = {}
    for i, arg in enumerate(call.args):
        if i < len(params):
            bound[params[i]] = arg
    for kw in call.keywords:
        if kw.arg:
            bound[kw.arg] = kw.value
    return bound


def _unify_ctor_locks(tree: _Tree) -> None:
    """A lock attribute assigned from a ctor param is the SAME node as
    whatever the caller passed — walk every construction site."""
    for ms in tree.modules:
        ctxs = [(None, fn) for fn in ms.functions.values()]
        for cs in ms.classes.values():
            ctxs += [(cs, m) for m in cs.methods.values()]
        for cls, fn in ctxs:
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                head = _call_head(sub)
                if not head:
                    continue
                cname = head.split(".")[-1]
                callee = tree.classes.get(cname)
                if callee is None or cname in tree.ambiguous_classes \
                        or "__init__" not in callee.methods:
                    continue
                ext = {attr: pp for attr, (pp, _l)
                       in callee.ext_candidates.items()
                       if (cname, attr) in tree.nodes}
                if not ext:
                    continue
                bound = _bind_ctor_args(callee.methods["__init__"], sub)
                for attr, param in ext.items():
                    arg = bound.get(param)
                    if arg is None:
                        continue
                    src = _resolve_lock_key(arg, cls, tree, {})
                    if src is not None:
                        tree.union((cname, attr), src)


def _resolve_lock_key(expr, cls: Optional[_ClassScan], tree: _Tree,
                      locals_locks: Dict[str, Tuple[str, str]],
                      module: Optional[str] = None):
    d = _dotted(expr)
    if d is None:
        return None
    parts = d.split(".")
    if parts[0] == "self" and cls is not None and len(parts) == 2:
        key = (cls.name, parts[1])
        return key if key in tree.nodes else None
    if len(parts) == 1:
        if parts[0] in locals_locks:
            return locals_locks[parts[0]]
        if module is not None:
            key = (f"@{module}", parts[0])
            if key in tree.nodes:
                return key
        return None
    # foreign receiver (`mgr._mu`): unique lock-attr fallback
    return tree.unique_attr.get(parts[-1])


# -- phase 3: summaries, edges, findings ------------------------------------

class _Analyzer:
    def __init__(self, tree: _Tree):
        self.t = tree
        self.findings: List[Finding] = []
        self.waived = 0
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self.src: Dict[str, List[str]] = {m.path: m.src_lines
                                          for m in tree.modules}
        self.mod_of: Dict[str, _ModuleScan] = {m.module: m
                                               for m in tree.modules}
        # function registry: key -> (node, class, module)
        self.fns: Dict[tuple, tuple] = {}
        for ms in tree.modules:
            for fname, fn in ms.functions.items():
                self.fns[("M", ms.module, fname)] = (fn, None, ms)
            for cs in ms.classes.values():
                if tree.classes.get(cs.name) is not cs:
                    continue
                for mname, m in cs.methods.items():
                    self.fns[("C", cs.name, mname)] = (m, cs, ms)
        self.acq: Dict[tuple, Set[tuple]] = {k: set() for k in self.fns}
        self.blk: Dict[tuple, Set[str]] = {k: set() for k in self.fns}
        self.calls: Dict[tuple, List[tuple]] = {k: [] for k in self.fns}
        self.call_sites: List[tuple] = []   # (caller, callee, heldset)
        self.accesses: List[tuple] = []     # (fnkey, cls, attr, store,
                                            #  line, local_held)

    # -- waiver-aware flag ------------------------------------------------
    def _flag(self, path: str, line: int, rule: str, msg: str) -> None:
        lines = self.src.get(path, [])
        for ln in (line, line - 1):
            if 1 <= ln <= len(lines):
                m = _WAIVER_RE.search(lines[ln - 1])
                if m and rule in [r.strip()
                                  for r in m.group(1).split(",")]:
                    self.waived += 1
                    return
        self.findings.append(Finding(path, line, rule, msg))

    # -- direct facts per function ---------------------------------------
    def run(self) -> None:
        for key in self.fns:
            self._walk_fn(key)
        self._fixpoint()
        self._second_pass()
        self._cycles()
        self._threads_rule()
        self._witness_rule()
        self._guarded_by_findings()

    def _walk_fn(self, key) -> None:
        node, cls, ms = self.fns[key]
        self._walk_block(key, node.body, [], cls, ms, 0, collect=True)

    def _fixpoint(self) -> None:
        changed = True
        while changed:
            changed = False
            for k in self.fns:
                for callee in self.calls[k]:
                    if callee in self.acq:
                        if not self.acq[callee] <= self.acq[k]:
                            self.acq[k] |= self.acq[callee]
                            changed = True
                        if not self.blk[callee] <= self.blk[k]:
                            self.blk[k] |= self.blk[callee]
                            changed = True

    # -- the statement walker --------------------------------------------
    def _walk_block(self, key, stmts, held, cls, ms, loops,
                    collect=False, emit=False) -> None:
        for st in stmts:
            self._walk_stmt(key, st, held, cls, ms, loops, collect, emit)

    def _walk_stmt(self, key, st, held, cls, ms, loops, collect, emit):
        t = self.t
        if isinstance(st, ast.With):
            acquired = []
            for item in st.items:
                self._exprs(key, item.context_expr, held, cls, ms, loops,
                            collect, emit)
                lk = _resolve_lock_key(item.context_expr, cls, t, {},
                                       ms.module)
                if lk is None or t.canon_kind(lk) not in _GUARDING:
                    continue
                name = t.canon(lk)
                if emit:
                    for h in held:
                        if h == name:
                            if t.canon_kind(lk) != "RLock":
                                self._flag(ms.path, st.lineno,
                                           "lock-cycle",
                                           f"{name} re-acquired while "
                                           f"already held and it is not "
                                           f"an RLock: self-deadlock")
                        else:
                            self.edges.setdefault(
                                (h, name), (ms.path, st.lineno))
                if collect:
                    self.acq[key].add(lk)
                acquired.append(name)
            self._walk_block(key, st.body, held + acquired, cls, ms,
                             loops, collect, emit)
        elif isinstance(st, (ast.If,)):
            self._exprs(key, st.test, held, cls, ms, loops, collect, emit)
            self._walk_block(key, st.body, held, cls, ms, loops,
                             collect, emit)
            self._walk_block(key, st.orelse, held, cls, ms, loops,
                             collect, emit)
        elif isinstance(st, (ast.While, ast.For)):
            # only a While with a real (non-constant) test counts as a
            # predicate loop for the condvar rule: `while True:` around
            # an if-guarded wait is exactly the seeded-mutation bug
            pred = 1 if (isinstance(st, ast.While)
                         and not (isinstance(st.test, ast.Constant)
                                  and st.test.value)) else 0
            for e in ([st.test] if isinstance(st, ast.While)
                      else [st.iter]):
                self._exprs(key, e, held, cls, ms, loops + pred, collect,
                            emit)
            self._walk_block(key, st.body, held, cls, ms, loops + pred,
                             collect, emit)
            self._walk_block(key, st.orelse, held, cls, ms, loops,
                             collect, emit)
        elif isinstance(st, ast.Try):
            self._walk_block(key, st.body, held, cls, ms, loops,
                             collect, emit)
            for h in st.handlers:
                self._walk_block(key, h.body, held, cls, ms, loops,
                                 collect, emit)
            self._walk_block(key, st.orelse, held, cls, ms, loops,
                             collect, emit)
            self._walk_block(key, st.finalbody, held, cls, ms, loops,
                             collect, emit)
        elif isinstance(st, _FUNC_NODES):
            # nested defs run where they are *invoked* (fault.retrying,
            # pool.submit); inlining at the definition approximates that
            # for acquire/blocking summaries without a closure analysis
            self._walk_block(key, st.body, held, cls, ms, loops,
                             collect, emit)
        else:
            for e in ast.iter_child_nodes(st):
                self._exprs(key, e, held, cls, ms, loops, collect, emit)

    def _exprs(self, key, expr, held, cls, ms, loops, collect, emit):
        if expr is None:
            return
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                self._call(key, sub, held, cls, ms, loops, collect, emit)
            elif isinstance(sub, ast.Attribute) and collect:
                self._attr_access(key, sub, held, cls)

    def _attr_access(self, key, node: ast.Attribute, held, cls):
        if cls is None:
            return
        if not (isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return
        if (cls.name, node.attr) in self.t.nodes:
            return   # the locks themselves are not guarded data
        store = isinstance(node.ctx, (ast.Store, ast.AugStore)) \
            if hasattr(ast, "AugStore") else isinstance(node.ctx, ast.Store)
        self.accesses.append((key, cls.name, node.attr, store,
                              node.lineno, frozenset(held)))
        # AugAssign target parses as Store-only; count the implied load
        if store:
            self.accesses.append((key, cls.name, node.attr, False,
                                  node.lineno, frozenset(held)))

    def _call(self, key, call: ast.Call, held, cls, ms, loops, collect,
              emit):
        t = self.t
        head = _call_head(call)
        if head is None:
            return
        parts = head.split(".")
        label = _BLOCKING_HEADS.get(head)
        if label is None and len(parts) >= 2 \
                and parts[-1] in _BLOCKING_ATTRS:
            label = _BLOCKING_ATTRS[parts[-1]]
        if label is None and len(parts) >= 2 and parts[-1] == "wait":
            # Condition.wait on the condvar you hold is the sanctioned
            # sleep (it releases that lock); anything else blocks.
            recv = call.func.value if isinstance(call.func,
                                                 ast.Attribute) else None
            lk = _resolve_lock_key(recv, cls, t, {}, ms.module) \
                if recv is not None else None
            if lk is not None and t.canon_kind(lk) == "Condition" \
                    and t.canon(lk) in held:
                if emit and loops == 0:
                    self._flag(ms.path, call.lineno, "condvar-wait",
                               f"{t.canon(lk)}.wait() outside a "
                               f"predicate loop: a stolen or spurious "
                               f"wakeup drops the wait silently — wrap "
                               f"in `while not <predicate>:`")
                others = [h for h in held if h != t.canon(lk)]
                if emit and others:
                    self._flag(ms.path, call.lineno, "lock-blocking",
                               f"{', '.join(sorted(set(others)))} held "
                               f"across {t.canon(lk)}.wait() — the wait "
                               f"releases only its own condvar")
                return
            label = ".wait"
        if label is not None:
            if collect:
                self.blk[key].add(label)
            if emit and held:
                self._flag(ms.path, call.lineno, "lock-blocking",
                           f"{', '.join(sorted(set(held)))} held across "
                           f"blocking/chaos-injectable call {label}"
                           f" ({head})")
            return
        callee = self._resolve_callee(parts, cls, ms)
        if callee is None:
            return
        if collect:
            self.calls[key].append(callee)
        if emit:
            self.call_sites.append((key, callee, frozenset(held)))
            if held:
                inner = {t.canon(k) for k in self.acq.get(callee, ())}
                for h in held:
                    for name in inner:
                        if name != h:
                            self.edges.setdefault(
                                (h, name), (ms.path, call.lineno))
                labels = self.blk.get(callee, ())
                if labels:
                    self._flag(
                        ms.path, call.lineno, "lock-blocking",
                        f"{', '.join(sorted(set(held)))} held across "
                        f"{head}(), which reaches blocking/"
                        f"chaos-injectable call(s): "
                        f"{', '.join(sorted(labels))}")

    def _resolve_callee(self, parts, cls, ms):
        t = self.t
        last = parts[-1]
        if len(parts) == 1:
            if last in t.classes and last not in t.ambiguous_classes \
                    and ("C", last, "__init__") in self.fns:
                return ("C", last, "__init__")
            if ("M", ms.module, last) in self.fns:
                return ("M", ms.module, last)
            return None
        if parts[0] == "self" and cls is not None:
            if len(parts) == 2 and ("C", cls.name, last) in self.fns:
                return ("C", cls.name, last)
            if len(parts) == 3:
                owner = getattr(cls, "attr_types", {}).get(parts[1])
                if owner and ("C", owner, last) in self.fns:
                    return ("C", owner, last)
            return None
        if len(parts) == 2:
            target = ms.aliases.get(parts[0])
            if target:
                # "from roc_tpu.train import checkpoint as _ckpt" ->
                # _ckpt.save_arrays -> roc_tpu.train.checkpoint
                for mod in (target, target.rsplit(".", 1)[0]):
                    if ("M", mod, last) in self.fns:
                        return ("M", mod, last)
            if parts[0] in t.classes \
                    and parts[0] not in t.ambiguous_classes \
                    and ("C", parts[0], last) in self.fns:
                return ("C", parts[0], last)
        return None

    def _second_pass(self) -> None:
        for key in self.fns:
            node, cls, ms = self.fns[key]
            self._walk_block(key, node.body, [], cls, ms, 0, emit=True)

    # -- rule: lock-order cycles ------------------------------------------
    def _cycles(self) -> None:
        adj: Dict[str, List[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
        color: Dict[str, int] = {}
        stack: List[str] = []

        def dfs(u):
            color[u] = 1
            stack.append(u)
            for v in sorted(adj.get(u, [])):
                if color.get(v, 0) == 0:
                    cyc = dfs(v)
                    if cyc:
                        return cyc
                elif color.get(v) == 1:
                    return stack[stack.index(v):] + [v]
            stack.pop()
            color[u] = 2
            return None

        for u in sorted(adj):
            if color.get(u, 0) == 0:
                cyc = dfs(u)
                if cyc:
                    path, line = self.edges.get(
                        (cyc[0], cyc[1]), ("<graph>", 1))
                    self._flag(path, line, "lock-cycle",
                               f"lock-order cycle (potential deadlock): "
                               f"{' -> '.join(cyc)}")
                    return

    # -- rule: spawned threads must be joinable ---------------------------
    def _threads_rule(self) -> None:
        for cs in self.t.classes.values():
            for sp in cs.spawns:
                joined = bool(sp["joined_local"])
                if sp["stored"]:
                    joined = joined or (
                        sp["stored"] in cs.shutdown_attrs if sp["pool"]
                        else sp["stored"] in cs.joined_attrs)
                if not joined:
                    what = "ThreadPoolExecutor" if sp["pool"] else \
                        f"thread (target={sp['target']}, " \
                        f"daemon={sp['daemon']})"
                    self._flag(cs.path, sp["line"], "thread-join",
                               f"{cs.name} spawns a {what} that no "
                               f".join()/.shutdown() in the class ever "
                               f"reaches — unreachable from close()")

    # -- rule: witness names must match their attribute -------------------
    def _witness_rule(self) -> None:
        for cs in self.t.classes.values():
            for attr, (kind, line, wname) in cs.locks.items():
                if wname is not None and wname != f"{cs.name}.{attr}":
                    self._flag(cs.path, line, "witness-name",
                               f"witness.trace name {wname!r} disagrees "
                               f"with its attribute "
                               f"{cs.name}.{attr} — the runtime witness "
                               f"would validate against the wrong node")

    # -- guarded-by inference ---------------------------------------------
    def _entry_held(self) -> Dict[tuple, Optional[frozenset]]:
        entry: Dict[tuple, Optional[frozenset]] = {}
        thread_targets = set()
        for cs in self.t.classes.values():
            for sp in cs.spawns:
                tgt = sp["target"]
                if tgt.startswith("self."):
                    thread_targets.add(("C", cs.name, tgt.split(".")[1]))
        for key in self.fns:
            kind, owner, name = key
            public = not name.startswith("_") or name == "__init__"
            if kind == "M" or public or key in thread_targets:
                entry[key] = frozenset()
            else:
                entry[key] = None   # unknown: no observed entry yet
        sites: Dict[tuple, List[tuple]] = {}
        for caller, callee, held in self.call_sites:
            sites.setdefault(callee, []).append((caller, held))
        changed = True
        while changed:
            changed = False
            for callee, lst in sites.items():
                cur = entry.get(callee)
                if cur == frozenset():
                    continue   # pinned entry point / already bottom
                acc = None
                for caller, held in lst:
                    ch = entry.get(caller)
                    if ch is None:
                        continue
                    eff = ch | held
                    acc = eff if acc is None else (acc & eff)
                if acc is None:
                    continue
                new = acc if cur is None else (cur & acc)
                if new != cur:
                    entry[callee] = new
                    changed = True
        return entry

    def _init_reachable(self) -> Set[tuple]:
        out: Set[tuple] = set()
        adj: Dict[tuple, Set[tuple]] = {}
        for caller, callee, _h in self.call_sites:
            adj.setdefault(caller, set()).add(callee)
        for cs in self.t.classes.values():
            key = ("C", cs.name, "__init__")
            if key not in self.fns:
                continue
            stack = [key]
            while stack:
                k = stack.pop()
                if k in out:
                    continue
                out.add(k)
                stack.extend(adj.get(k, ()))
        return out

    def compute_guarded(self) -> Dict[str, str]:
        self._entry = self._entry_held()
        self._exempt = self._init_reachable()
        # classes that own a guarding lock are in scope
        in_scope = {cs.name for cs in self.t.classes.values()
                    if any(k in _GUARDING
                           for k, _l, _w in cs.locks.values())}
        per_attr: Dict[Tuple[str, str], dict] = {}
        for key, cname, attr, store, line, local_held in self.accesses:
            if cname not in in_scope:
                continue
            e = self._entry.get(key)
            if e is None:
                continue   # never-called private method: no context
            held = {h for h in local_held} | set(e)
            rec = per_attr.setdefault((cname, attr), {
                "under": {}, "stores_under": {}, "bare_stores": []})
            if held:
                for h in held:
                    rec["under"][h] = rec["under"].get(h, 0) + 1
                    if store:
                        rec["stores_under"][h] = \
                            rec["stores_under"].get(h, 0) + 1
            elif store:
                mname = key[2]
                rec["bare_stores"].append((key, mname, line))
        guarded: Dict[str, str] = {}
        self._guard_viol: List[tuple] = []
        for (cname, attr), rec in sorted(per_attr.items()):
            if not rec["under"]:
                continue
            under = rec["under"]
            lock = sorted(under, key=lambda h, _u=under: (-_u[h], h))[0]
            # "consistently accessed under L": at least 3 accesses under
            # it, and bare *stores* outside construction stay a strict
            # minority (they are the bug, not the convention).  Stores
            # under the lock are not required — a deque filled and
            # drained under a condvar is guarded data even though its
            # binding never changes after __init__.
            bad = [b for b in rec["bare_stores"]
                   if b[0] not in self._exempt and b[1] != "__init__"]
            if under[lock] < 3 or len(bad) >= under[lock]:
                continue
            guarded[f"{cname}.{attr}"] = lock
            for fkey, mname, line in bad:
                cs = self.t.classes[cname]
                self._guard_viol.append(
                    (cs.path, line, cname, attr, lock, mname))
        return guarded

    def _guarded_by_findings(self) -> None:
        self.guarded = self.compute_guarded()
        for path, line, cname, attr, lock, mname in self._guard_viol:
            self._flag(path, line, "unguarded-attr",
                       f"{cname}.{attr} is guarded by {lock} "
                       f"(>=3 accesses incl. stores) but {mname}() "
                       f"stores it with no lock held — a thread-"
                       f"reachable unguarded write")


# -- public API --------------------------------------------------------------

def _iter_py(paths) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        out.append(os.path.join(root, fn))
        elif p.endswith(".py"):
            out.append(p)
    return sorted(out)


def analyze_paths(paths=("roc_tpu",)) -> Report:
    modules = []
    for path in _iter_py(paths):
        with open(path, encoding="utf-8") as f:
            ms = _scan_module(path, f.read())
        if ms is not None:
            modules.append(ms)
    return _analyze(modules)


def analyze_source(src: str, path: str = "fixture.py") -> Report:
    ms = _scan_module(path, src)
    return _analyze([ms] if ms is not None else [])


def _analyze(modules) -> Report:
    tree = _Tree(modules)
    _unify_ctor_locks(tree)
    an = _Analyzer(tree)
    an.run()
    # canonical lock table: external nodes fold into their creation site
    locks, seen = [], set()
    for key in sorted(tree.nodes, key=lambda k: tree.nodes[k].name):
        canon = tree.canon(key)
        if canon in seen:
            continue
        seen.add(canon)
        root = tree.nodes[tree._find(key)]
        locks.append(root)
    threads = []
    for cs in sorted(tree.classes.values(), key=lambda c: c.name):
        for sp in sorted(cs.spawns, key=lambda s: s["line"]):
            target = sp["target"]
            if target.startswith("self."):
                target = f"{cs.name}.{target[5:]}"
            stored = f"{cs.name}.{sp['stored']}" if sp["stored"] else \
                ("<local>" if sp["local"] else "")
            joined = bool(sp["joined_local"]) or (
                sp["stored"] in (cs.shutdown_attrs if sp["pool"]
                                 else cs.joined_attrs))
            threads.append(ThreadSpawn(target, sp["daemon"], stored,
                                       joined, sp["pool"], cs.path,
                                       sp["line"]))
    edges = dict(an.edges)
    for a, b, _reason in DECLARED_EDGES:
        edges.setdefault((a, b), ("<declared>", 0))
    findings = sorted(an.findings, key=lambda f: (f.path, f.line, f.rule))
    return Report(locks=locks, threads=threads, edges=edges,
                  guarded_by=an.guarded, findings=findings,
                  waived=an.waived)


def report_dict(report: Report) -> dict:
    """Deterministic baseline payload.  Line numbers are deliberately
    excluded: the baseline pins the *discipline* (nodes, edges, facts),
    not the layout — unrelated edits must not churn it."""
    return {
        "locks": [{"name": lk.name, "kind": lk.kind, "path": lk.path,
                   "witness": lk.witness_name}
                  for lk in sorted(report.locks, key=lambda l: l.name)],
        "threads": [{"target": th.target, "daemon": th.daemon,
                     "stored": th.stored, "joined": th.joined,
                     "pool": th.pool, "path": th.path}
                    for th in sorted(report.threads,
                                     key=lambda t: (t.path, t.target))],
        "edges": sorted([a, b] for a, b in report.edges),
        "declared_edges": [[a, b, r] for a, b, r in DECLARED_EDGES],
        "guarded_by": {k: report.guarded_by[k]
                       for k in sorted(report.guarded_by)},
    }


def load_baseline(path: str = BASELINE_PATH) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def save_baseline(report: Report, path: str = BASELINE_PATH) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report_dict(report), f, indent=1, sort_keys=True)
        f.write("\n")


def diff_baseline(report: Report, path: str = BASELINE_PATH) -> List[str]:
    """Exact-diff the live report against the committed baseline — the
    budgets.json contract: any drift is a violation until regenerated
    deliberately with --update-threads."""
    if not os.path.exists(path):
        return [f"no committed baseline at {path} — run "
                f"tools/roclint.py --update-threads"]
    want = load_baseline(path)
    got = report_dict(report)
    out = []
    for section in sorted(set(want) | set(got)):
        if want.get(section) != got.get(section):
            w = json.dumps(want.get(section), sort_keys=True)
            g = json.dumps(got.get(section), sort_keys=True)
            out.append(f"threads.json drift in {section!r}:\n"
                       f"  committed: {w}\n  current:   {g}")
    return out


# -- selftest: the seeded-mutation fixture matrix ---------------------------

_FIX_CLEAN = '''
import threading

class Worker:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()
        self.cv = threading.Condition()
        self.items = []
        self.done = False
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        while True:
            with self.cv:
                while not self.items and not self.done:
                    self.cv.wait(timeout=0.1)
                if self.done:
                    return
                self.items.pop()

    def push(self, x):
        with self.cv:
            self.items.append(x)
            self.done = False
            self.cv.notify()

    def transfer(self):
        with self.a:
            with self.b:
                pass

    def close(self):
        with self.cv:
            self.done = True
            self.cv.notify_all()
        self._t.join()
'''

_MUT_INVERSION = _FIX_CLEAN + '''
    def transfer_back(self):
        with self.b:
            with self.a:
                pass
'''

_MUT_UNGUARDED = _FIX_CLEAN + '''
    def poison(self):
        self.done = True
'''

_MUT_WAITLESS = _FIX_CLEAN.replace(
    """                while not self.items and not self.done:
                    self.cv.wait(timeout=0.1)""",
    """                if not self.items:
                    self.cv.wait(timeout=0.1)""")

_MUT_UNJOINED = _FIX_CLEAN.replace("        self._t.join()\n", "")

_MUT_BLOCKING = _FIX_CLEAN + '''
    def flush(self):
        import os
        with self.a:
            os.fsync(0)
'''

_MUT_WITNESS_NAME = _FIX_CLEAN.replace(
    "self.a = threading.Lock()",
    'self.a = witness.trace("Other.z", threading.Lock())')


def selftest(verbose: bool = True) -> int:
    """Seeded-mutation matrix + witness mechanics; 0 on success."""
    failures = []

    def check(label, cond):
        if verbose:
            print(f"#   threads selftest: {label}: "
                  f"{'ok' if cond else 'FAIL'}")
        if not cond:
            failures.append(label)

    clean = analyze_source(_FIX_CLEAN)
    check("clean fixture has zero findings", not clean.findings)
    check("clean fixture derives the a->b edge",
          ("Worker.a", "Worker.b") in clean.edges)
    check("clean fixture infers items guarded-by cv",
          clean.guarded_by.get("Worker.items") == "Worker.cv")
    check("clean fixture infers done guarded-by cv",
          clean.guarded_by.get("Worker.done") == "Worker.cv")

    def rules(rep):
        return {f.rule for f in rep.findings}

    check("seeded lock inversion is caught (lock-cycle)",
          "lock-cycle" in rules(analyze_source(_MUT_INVERSION)))
    check("seeded dropped guard is caught (unguarded-attr)",
          "unguarded-attr" in rules(analyze_source(_MUT_UNGUARDED)))
    check("seeded waitless condvar wait is caught (condvar-wait)",
          "condvar-wait" in rules(analyze_source(_MUT_WAITLESS)))
    check("seeded unjoined thread is caught (thread-join)",
          "thread-join" in rules(analyze_source(_MUT_UNJOINED)))
    check("seeded lock-held-across-fsync is caught (lock-blocking)",
          "lock-blocking" in rules(analyze_source(_MUT_BLOCKING)))
    check("witness name mismatch is caught (witness-name)",
          "witness-name" in rules(analyze_source(_MUT_WITNESS_NAME)))

    # witness mechanics: armed proxies record pairs, the validator
    # checks them against a graph, disarmed trace is a passthrough
    import threading as _th

    from roc_tpu.analysis import witness as w
    was = w.armed()
    try:
        w.arm(False)
        raw = _th.Lock()
        check("disarmed trace returns the primitive untouched",
              w.trace("X.a", raw) is raw)
        w.reset()
        with w.trace("X.a", _th.Lock()):
            pass
        check("disarmed witness records zero pairs", w.records() == 0)

        w.arm(True)
        w.reset()
        la = w.trace("X.a", _th.Lock())
        lb = w.trace("X.b", _th.Lock())
        with la:
            with lb:
                pass
        check("armed witness records the (a, b) pair",
              w.observed_pairs().get(("X.a", "X.b"), 0) >= 1)
        check("validator accepts an in-graph order",
              w.validate(edges=[("X.a", "X.b")]) == [])
        check("validator flags an off-graph order",
              len(w.validate(edges=[("X.b", "X.a")])) == 1)
        check("validator accepts a transitively sanctioned order",
              w.validate(edges=[("X.a", "X.c"), ("X.c", "X.b")]) == [])
        w.reset()
    finally:
        w.arm(was)

    if verbose:
        n = len(failures)
        print(f"# threads selftest: {n} failure(s)")
    return 1 if failures else 0


def _main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m roc_tpu.analysis.threads",
        description="whole-tree lock-discipline analyzer (roc-threads)")
    ap.add_argument("--selftest", action="store_true",
                    help="run the seeded-mutation fixture matrix")
    ap.add_argument("--update", action="store_true",
                    help="regenerate threads.json from the current tree")
    ap.add_argument("paths", nargs="*", default=None)
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    rep = analyze_paths(args.paths or ("roc_tpu",))
    if args.update:
        save_baseline(rep)
        print(f"# threads: wrote {BASELINE_PATH}")
        return 0
    for f in rep.findings:
        print(f)
    for line in diff_baseline(rep):
        print(line)
    bad = bool(rep.findings) or bool(diff_baseline(rep))
    print(f"# threads: {len(rep.findings)} finding(s), "
          f"{len(rep.edges)} edge(s), {len(rep.guarded_by)} guarded-by "
          f"fact(s), {rep.waived} waived")
    return 3 if bad else 0


if __name__ == "__main__":
    raise SystemExit(_main())
