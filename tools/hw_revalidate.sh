#!/bin/bash
# One-shot hardware revalidation after a tunnel outage (or a new round).
#
# ORDERING CONTRACT (VERDICT r4 weak #3): the first thing a window buys is
# the canonical bench of shipped defaults — round 2's only window was
# ~40 min and four rounds produced null driver artifacts while this script
# spent its first ~20 min on kernel tests.  Steps, highest-value first:
#   1. bench.py on shipped defaults (SLOT=128, auto-geometry) — headline
#   2. products-shape A/B (matmul vs auto-binned vs +reorder)
#   3. fp32-exact + GAT + overcommit benches
#   4. TPU-gated kernel tests
#   5. out-of-core streaming A/B, serving bench, fault drill (SIGTERM ->
#      resume parity; seeded chaos twin on the streamed path)
#   6. group-count / constant / sparse-preset sweeps
# Each step is timeout-guarded so a wedged compile can't eat the window.
# Usage:  bash tools/hw_revalidate.sh [start-step]  (from repo root)
set -u
cd "$(dirname "$0")/.."
LOG=/tmp/hw_revalidate.log
START=${1:-0}
case "$START" in
    [0-6]) ;;
    *) echo "usage: $0 [start-step 0-6]" >&2; exit 2 ;;
esac
: > "$LOG"

note() { echo "== $*" | tee -a "$LOG"; }

note "probe"
timeout 60 python -c "import jax; print(jax.devices())" 2>&1 | tail -1 \
    | tee -a "$LOG" || { note "tunnel down; aborting"; exit 1; }

if [ "$START" -le 0 ]; then
note "0. static analysis gate (roclint + collective budget audit) — no"
note "   TPU minutes spent: catches host syncs / budget drift before the"
note "   window burns on a program we would reject anyway"
timeout 120 python tools/roclint.py 2>&1 | tail -2 | tee -a "$LOG" \
    || { note "roclint findings; fix or waive before burning the window"; \
         exit 1; }
timeout 600 python tools/roclint.py --audit --no-lint 2>&1 | tail -2 \
    | tee -a "$LOG" || { note "budget audit red; investigate first"; exit 1; }
fi

if [ "$START" -le 1 ]; then
note "1. bench shipped defaults (THE headline; expect binned, ~0.63 s/epoch)"
timeout 1800 python bench.py 2>&1 | tail -3 | tee -a "$LOG"
fi

if [ "$START" -le 2 ]; then
note "2. products-shape single-chip A/B (the north-star graph:"
note "   matmul vs binned-auto-geometry vs +RCM-reorder)"
# ROC_BENCH_SHAPE=products now presets nodes/degree/layers by itself
PROD="env ROC_BENCH_SHAPE=products ROC_BENCH_EPOCHS=5"
# SAME-PROCESS A/B (round-5 anomaly fix, docs/PERF.md): both legs in one
# invocation, per-epoch samples in the artifact — separate invocations
# are how the 8.5x forced-vs-auto artifact happened.  With the refit
# cost model auto now resolves to a sparse binned preset here, so the
# legs are the real matmul-vs-binned comparison.
$PROD ROC_BENCH_AB=matmul,auto timeout 6000 python bench.py 2>&1 \
    | tail -2 | tee -a "$LOG"
# with the RCM locality pass (auto keeps the order only on a measured
# padded-row gain): choose_geometry should then pick a binned geometry
$PROD ROC_BENCH_BACKEND=auto ROC_BENCH_REORDER=auto timeout 3000 \
    python bench.py 2>&1 | tail -2 | tee -a "$LOG"
# hierarchical-locality variant (inter edges ring-adjacent, the structure
# real co-purchase graphs have): A/B the reorder win where it can exist —
# the uniform-inter runs above are the locality worst case
for rr in 0 auto; do
    $PROD ROC_BENCH_BACKEND=auto ROC_BENCH_INTER=ring ROC_BENCH_REORDER=$rr \
        timeout 3000 python bench.py 2>&1 | tail -2 | tee -a "$LOG"
done
fi

if [ "$START" -le 3 ]; then
note "3a. fp32-exact epoch on the binned kernels (target: <= 1.0 s)"
ROC_BENCH_PRECISION=exact ROC_BENCH_BACKEND=binned ROC_BENCH_EPOCHS=5 \
    timeout 1800 python bench.py 2>&1 | tail -2 | tee -a "$LOG"

note "3b. GAT shape sweep, plan-backend attention (target: within ~2x of"
note "    GCN at the canonical shape; record each leg's roofline_frac in"
note "    docs/PERF.md — the sweep shows where the attention path falls"
note "    off the roofline as width/depth grow)"
for gat_shape in 602-64-41 602-128-41 602-64-64-41; do
    note "   ROC_BENCH_LAYERS=$gat_shape"
    ROC_BENCH_MODEL=gat ROC_BENCH_LAYERS=$gat_shape ROC_BENCH_HEADS=4 \
        ROC_BENCH_EPOCHS=5 timeout 1800 python bench.py 2>&1 \
        | tail -2 | tee -a "$LOG"
done

note "3c. overcommit: 4 parts on the 1 bench chip (multi-part paths:"
note "    halo all_to_all, per-part plans, psum)"
timeout 900 python -m roc_tpu -dataset reddit-small -layers 602-128-41 \
    -e 10 -parts 4 -v 2>&1 | tail -2 | tee -a "$LOG"
timeout 900 python -m roc_tpu -dataset reddit-small -layers 602-128-41 \
    -e 10 -parts 4 -no-halo -v 2>&1 | tail -2 | tee -a "$LOG"
timeout 900 python -m roc_tpu -dataset reddit-small -layers 602-64-41 \
    -e 10 -parts 4 -model gat -heads 2 -aggr-backend matmul -v 2>&1 \
    | tail -2 | tee -a "$LOG"

note "3d. balancer dryrun: 4-part overcommit with the online cost-model"
note "    load balancer (probe -> fit -> reshard under frozen shapes;"
note "    expect 'balance@' lines, reshard only if pred gain >= 5%)"
timeout 900 python -m roc_tpu -dataset reddit-small -layers 602-128-41 \
    -e 8 -parts 4 -balance-every 2 -v 2>&1 | tail -4 | tee -a "$LOG"

note "3e. memory-plan dryrun (roc_tpu/memory): DP under a deliberately"
note "    tight budget — expect a 'mem-plan[auto/dp]' line with >=1 remat"
note "    layer, and the bench artifact's memory block comparing predicted"
note "    vs measured (memory_stats) peak HBM"
ROC_BENCH_MEM=1 ROC_MEM_PLAN=auto ROC_MEM_BUDGET=4g ROC_BENCH_EPOCHS=5 \
    timeout 1800 python bench.py 2>&1 | tail -2 | tee -a "$LOG"
timeout 900 python -m roc_tpu -dataset reddit-small -layers 602-128-41 \
    -e 10 -parts 4 -mem-plan auto -mem-budget 2g -v 2>&1 \
    | tail -3 | tee -a "$LOG"

note "3f. bf16-storage A/B at the canonical Reddit GCN shape: paired legs"
note "    (fp32 storage, then ROC_BF16_STORAGE=1) — compare epoch time"
note "    (expect the bf16 leg faster where the run is staging/halo"
note "    byte-bound; artifact 'dtype' field distinguishes the pair) and"
note "    final loss (parity gate: |bf16 - fp32| within 1e-2)"
ROC_BENCH_EPOCHS=5 timeout 1800 python bench.py 2>&1 \
    | tail -2 | tee -a "$LOG"
ROC_BF16_STORAGE=1 ROC_BENCH_EPOCHS=5 timeout 1800 python bench.py 2>&1 \
    | tail -2 | tee -a "$LOG"
# sharded loss A/B (halo wire rides bf16; -v prints per-epoch loss)
timeout 900 python -m roc_tpu -dataset reddit-small -layers 602-128-41 \
    -e 10 -parts 4 -v 2>&1 | tail -2 | tee -a "$LOG"
timeout 900 python -m roc_tpu -dataset reddit-small -layers 602-128-41 \
    -e 10 -parts 4 -bf16-storage -v 2>&1 | tail -2 | tee -a "$LOG"

note "3g. obs-trace capture: the shipped-defaults bench under ROC_OBS=1 —"
note "    hands back the first HOST-side span trace from real hardware"
note "    (trace.json loads in Perfetto next to an xprof trace) plus the"
note "    watchdog verdict against the budget-seeded EWMA; artifacts under"
note "    /tmp/roc_obs_hw"
ROC_OBS=1 ROC_OBS_DIR=/tmp/roc_obs_hw ROC_BENCH_EPOCHS=5 \
    timeout 1800 python bench.py 2>&1 | tail -2 | tee -a "$LOG"
timeout 120 python -m roc_tpu.obs report -dir /tmp/roc_obs_hw 2>&1 \
    | tee -a "$LOG"
timeout 120 python -m roc_tpu.obs calibration -dir /tmp/roc_obs_hw 2>&1 \
    | tee -a "$LOG"

note "3h. per-kernel microbench on the chip: times every Pallas variant"
note "    (two-pass p1/p2, flat, fused, mega fwd/bwd, matmul) in isolation"
note "    across the geometry presets and COMMITS the measured table into"
note "    tools/kernel_budgets.json — the balance cost model and"
note "    choose_geometry warm-start from it (interpret=false tables only;"
note "    the CPU table in the repo is schema ballast, never trusted)."
note "    Review + commit the kernel_budgets.json diff after the window."
KB_DEVICE=1 KB_REPS=5 timeout 1800 \
    python tools/kernel_bench.py --update 2>&1 | tail -20 | tee -a "$LOG"

note "    ... then the geometry AUTOTUNER (roc_tpu/tune): successive-"
note "    halving sweep of the kernel-config lattice at the device shapes,"
note "    winners persisted content-keyed into tuned.json beside the plan"
note "    cache (choose_geometry consults them before its analytic model"
note "    on the very next run), the refit stage re-solving chunk_s /"
note "    slot_dma_s / flat-DMA / mm_chunk_s from the trial records into"
note "    the kernel_budgets measured table, and the calibration report"
note "    grading every trial's predict/measure pair.  One command:"
timeout 3600 python -m roc_tpu.tune --device --shapes device \
    --refit --update 2>&1 | tail -25 | tee -a "$LOG"
fi

if [ "$START" -le 4 ]; then
note "4. TPU-gated kernel tests (incl. H=41, fallback kernel, avg, flat)"
PYTHONPATH=/root/.axon_site:$PWD timeout 1200 python tests/test_tpu_hw.py \
    2>&1 | tail -3 | tee -a "$LOG"

note "4b. flat-vs-slot-padded A/B at Reddit scale (same shape, flat=0/1;"
note "    model predicts ~37% fewer grid steps — record the measured ratio"
note "    in docs/PERF.md and re-fit the flat DMA constant from it)"
for flat in 0 1; do
    timeout 900 python tools/sweep_binned.py 512 4096 128 512 4096 \
        2097152 $flat 2>&1 | tail -1 | tee -a "$LOG"
done

note "4c. megakernel FULL TRAIN-STEP A/B at the mega-shard shape: three"
note "    legs, same seed — (1) two-pass baseline, (2) forward-only fusion"
note "    (-megafuse with the backward killed via ROC_MEGA_BWD=0), (3)"
note "    forward+backward fusion (-megafuse, fused VJP).  The -v losses"
note "    must agree to ~1e-3 across all three; leg 2 vs 1 isolates the"
note "    forward win, leg 3 vs 2 isolates the backward win (the fused VJP"
note "    skips the [rows, H] cotangent round trip — kernel_budgets.json"
note "    megakernel_bwd predicts 10-vs-28 backward layer steps and a"
note "    >= 2x per-layer train-step HBM drop vs forward-only fusion)."
note "    Record all three epoch times + the GIN/GCN pair in docs/PERF.md."
note "    ROC_BINNED_GEOM pins flat on ALL legs so the measured deltas are"
note "    fusion, not the cost model's geometry pick."
for leg in "::" "-megafuse:0:" "-megafuse::"; do
    mf=${leg%%:*}; rest=${leg#*:}; kill=${rest%%:*}
    ROC_BINNED_GEOM=flat ROC_MEGA_BWD=$kill timeout 900 python -m roc_tpu \
        -dataset mega-shard -layers 64-128-8 -model gin \
        -aggr-backend binned -e 10 $mf -v 2>&1 | tail -2 | tee -a "$LOG"
done
# norm-folded GCN leg (round 12: GCN is mega-eligible end to end; the
# fold pre/post-scales by D^-1/2 around the fused kernel)
for mf in "" "-megafuse"; do
    ROC_BINNED_GEOM=flat timeout 900 python -m roc_tpu \
        -dataset mega-shard -layers 64-128-8 -model gcn \
        -aggr-backend binned -e 10 $mf -v 2>&1 | tail -2 | tee -a "$LOG"
done

note "4d. cross-layer fusion-region FULL TRAIN-STEP A/B (round 16): the"
note "    residual-free deep GCN chain (gcn-chain) at three region caps,"
note "    same seed — depth 1 (per-layer fusion, the PR-10 program),"
note "    depth 2 (two-layer regions), full (the whole hidden stack in"
note "    one grid).  The -v losses must agree to ~1e-3 across all three;"
note "    depth 2 vs 1 isolates the first inter-layer boundary's HBM"
note "    round trip, full vs 2 the rest (kernel_budgets.json"
note "    megakernel_xlayer predicts a depth-2 region at <= 0.51x the"
note "    per-layer mega+bwd train-step HBM per layer at the Reddit"
note "    shape).  Record all three epoch times in docs/PERF.md round 16."
for fd in 1 2 0; do
    ROC_BINNED_GEOM=flat timeout 900 python -m roc_tpu \
        -dataset mega-shard -layers 64-128-128-8 -model gcn-chain \
        -aggr-backend binned -e 10 -megafuse -fusion-depth $fd -v 2>&1 \
        | tail -2 | tee -a "$LOG"
done

note "4e. fused GAT attention A/B (round 19): same seed, plan attention"
note "    backend, fused attention megakernel on vs ROC_NO_GATFUSE=1"
note "    (the unfused gat_attend_plan composition).  The -v losses must"
note "    agree to ~1e-3; the fused leg's epoch time is the round-19"
note "    claim of record (kernel_budgets.json gat_fused predicts"
note "    <= 0.6x unfused train-step HBM at every committed shape)."
note "    Measured gat_fused_hbm_bytes also rides kernel_bench --filter"
note "    gat (calibration ledger joins it to the plan-build prediction)."
for gf in "ROC_NO_GATFUSE=1" ""; do
    env $gf ROC_BINNED_GEOM=flat timeout 900 python -m roc_tpu \
        -dataset mega-shard -layers 64-128-8 -model gat -heads 2 \
        -aggr-backend matmul -e 10 -megafuse -v 2>&1 \
        | tail -2 | tee -a "$LOG"
done
fi

if [ "$START" -le 5 ]; then
note "5. out-of-core streaming A/B at the canonical shape: paired legs"
note "   (in-core SPMD, then ROC_BENCH_STREAM=1 rotating 4 shards through"
note "   2 device slots).  Record both epoch times and the streamed leg's"
note "   stream.stream_overlap_frac (the artifact's measured transfer/"
note "   compute overlap) in docs/PERF.md round 11 — the cost model"
note "   predicts near-full overlap when per-shard compute exceeds the"
note "   staging-DMA time of one slot's table bytes"
ROC_BENCH_EPOCHS=5 timeout 1800 python bench.py 2>&1 \
    | tail -2 | tee -a "$LOG"
ROC_BENCH_STREAM=1 ROC_STREAM_SLOTS=2 ROC_BENCH_EPOCHS=5 \
    timeout 1800 python bench.py 2>&1 | tail -2 | tee -a "$LOG"
note "   round-20 tier legs: bf16-streamed (wire bytes must land near"
note "   0.5x the fp32 streamed leg's stream.bytes_per_epoch — the"
note "   kernel_budgets stream row's <= 0.55x claim, measured), then the"
note "   NVMe spill tier (same slots; record stream.stream_spill_stall_frac"
note "   — the cost model predicts near-zero when spill reads hide under"
note "   the ring like host reads do).  Artifacts stamp stream_dtype/"
note "   stream_spill, so the paired legs stay distinguishable."
ROC_BENCH_STREAM=1 ROC_STREAM_SLOTS=2 ROC_BF16_STORAGE=1 \
    ROC_BENCH_EPOCHS=5 timeout 1800 python bench.py 2>&1 \
    | tail -2 | tee -a "$LOG"
SPILL_DIR=$(mktemp -d /tmp/roc_spill.XXXXXX)
ROC_BENCH_STREAM=1 ROC_STREAM_SLOTS=2 ROC_STREAM_SPILL="$SPILL_DIR" \
    ROC_BENCH_EPOCHS=5 timeout 1800 python bench.py 2>&1 \
    | tail -2 | tee -a "$LOG"
rm -rf "$SPILL_DIR"
# driver-path smoke on real hardware: >2x-budget rotation + live obs
timeout 900 python -m roc_tpu -dataset reddit-small -layers 602-128-41 \
    -e 10 -parts 4 -stream -stream-slots 2 -v 2>&1 | tail -3 | tee -a "$LOG"

note "5b. serving latency/throughput on the chip (roc_tpu/serve): warm-"
note "    cache cold start (plan_builds must be 0), then open-loop p50/p99"
note "    at stepped offered QPS — record the knee (where p99 detaches"
note "    from p50) and the cold start in docs/PERF.md's serving table,"
note "    and compare measured p50 against the roofline forward-time"
note "    prediction (the serve-p50 ledger pair in the calibration report)"
timeout 1200 env ROC_SERVE_BENCH_DATASET=reddit-small \
    ROC_SERVE_BENCH_REQUESTS=500 ROC_SERVE_BENCH_QPS=50 \
    python tools/serve_bench.py 2>&1 | tail -2 | tee -a "$LOG"
for qps in 100 200 400; do
    note "   offered qps=$qps"
    timeout 1200 env ROC_SERVE_BENCH_DATASET=reddit-small \
        ROC_SERVE_BENCH_REQUESTS=500 ROC_SERVE_BENCH_QPS=$qps \
        python tools/serve_bench.py 2>&1 | tail -1 | tee -a "$LOG"
done
# riding-along capture on the canonical bench shape (serve block in the
# bench artifact; excluded from vs_baseline / the canonical persist)
ROC_BENCH_SERVE=1 ROC_BENCH_EPOCHS=5 timeout 1800 python bench.py 2>&1 \
    | tail -2 | tee -a "$LOG"

note "5c. fault drill on the chip (roc_tpu/fault): three legs."
note "    (i) SIGTERM mid-run — the trainer must finish the epoch, write"
note "    the checkpoint, and exit cleanly; (ii) -resume from that"
note "    checkpoint completes and the final loss matches the"
note "    uninterrupted reference leg; (iii) a seeded chaos leg (retried"
note "    ring fetch + lux read, one injected NaN step) on the streamed"
note "    path must finish with a finite loss within 1e-3 of its own"
note "    fault-free twin.  Chaos legs NEVER feed perf baselines — their"
note "    epoch times include injected sleeps and retries."
CKPT=/tmp/roc_fault_drill.npz
DRILL="python -m roc_tpu -dataset reddit-small -layers 602-64-41 -e 12 -v"
rm -f "$CKPT"
timeout 900 $DRILL -ckpt "$CKPT" -ckpt-every 2 > /tmp/roc_drill_a.log 2>&1 &
DRILL_PID=$!
sleep 45; kill -TERM "$DRILL_PID" 2>/dev/null
wait "$DRILL_PID"
tail -2 /tmp/roc_drill_a.log | tee -a "$LOG"
grep -q "exiting cleanly" /tmp/roc_drill_a.log \
    || note "   drill note: no clean-exit line (run may have finished first)"
[ -f "$CKPT" ] || note "   drill RED: SIGTERM leg left no checkpoint"
timeout 900 $DRILL -ckpt "$CKPT" -resume 2>&1 | tail -2 | tee -a "$LOG"
timeout 900 $DRILL 2>&1 | tail -2 | tee -a "$LOG"   # uninterrupted reference
# chaos twin pair on the streamed path (same seed; compare final losses)
STREAMED="python -m roc_tpu -dataset reddit-small -layers 602-64-41 \
    -e 10 -parts 2 -stream -stream-slots 2 -v"
timeout 900 $STREAMED 2>&1 | tail -2 | tee -a "$LOG"
ROC_FAULT="seed=5,ring.fetch=2,lux.read=1,step.nan=1" timeout 900 \
    $STREAMED 2>&1 | tail -3 | tee -a "$LOG"

note "5d. on-device delta drill (roc_tpu/serve/delta): mixed add/retire"
note "    churn on the real chip — the serve selftest's delta leg pins"
note "    zero retraces + zero plan rebuilds + journal restart-replay"
note "    parity, then the fault selftest's delta stage runs the kill-"
note "    window matrix (lost-before-WAL vs replayed-after-WAL, torn"
note "    tail truncated).  The bench's delta block records apply"
note "    p50/p99 fault-free; chaos legs NEVER feed perf baselines."
timeout 900 python -m roc_tpu.serve --selftest 2>&1 | tail -3 | tee -a "$LOG"
timeout 600 python -m roc_tpu.fault --selftest 2>&1 | tail -2 | tee -a "$LOG"
timeout 1200 env ROC_SERVE_BENCH_DATASET=reddit-small \
    ROC_SERVE_BENCH_REQUESTS=200 ROC_SERVE_BENCH_QPS=50 \
    ROC_SERVE_BENCH_DELTAS=100 \
    python tools/serve_bench.py 2>&1 | tail -1 | tee -a "$LOG"

note "5e. on-device fleet drill (roc_tpu/fleet): 3 replicas behind the"
note "    router on the real chip — WAL-shipped segment replication in"
note "    seq lockstep (bitwise parity vs a single-engine oracle), a"
note "    seeded replica kill + snapshot catch-up mid-stream, typed"
note "    backpressure counted.  Then the bench's --fleet sweep records"
note "    router p50/p99 + shed rate + replication lag p99 fault-free"
note "    (the fleet block of BENCH_SERVE.json)."
timeout 900 python -m roc_tpu.fleet --selftest 2>&1 | tail -4 | tee -a "$LOG"
timeout 1800 env ROC_SERVE_BENCH_DATASET=reddit-small \
    ROC_SERVE_BENCH_REQUESTS=200 ROC_SERVE_BENCH_QPS=50 \
    ROC_SERVE_BENCH_DELTAS=100 \
    python tools/serve_bench.py --fleet 3 2>&1 | tail -1 | tee -a "$LOG"
fi

if [ "$START" -le 6 ]; then
note "6. group-count sweep (fewer groups -> less phase-1 rounding)"
for grt in 2097152 4194304 8388608; do
    note "   ROC_BINNED_GROUP_ROWS=$grt"
    ROC_BINNED_GROUP_ROWS=$grt ROC_BENCH_BACKEND=binned \
        timeout 1800 python bench.py 2>&1 | tail -2 | tee -a "$LOG"
done

note "6b. constant sweep round 2"
timeout 5400 python tools/sweep_binned.py 2>&1 | tee -a "$LOG"

note "6c. sparse-preset sweep at products shape (re-fit choose_geometry's"
note "    cost model constants from whatever this measures)"
SWEEP_SHAPE=products SWEEP_N=2449029 SWEEP_E=125000000 SWEEP_TIMEOUT_S=1800 \
    timeout 6000 python tools/sweep_binned.py 2>&1 | tee -a "$LOG"
fi

note "done — record winners in docs/PERF.md + BASELINE.md, update"
note "ROC_BINNED_GROUP_ROWS default / native BN_* constants if changed"
