"""Edge-sharded aggregation (-edge-shard): exactly-equal edge blocks +
psum_scatter.  Must be unobservable vs vertex sharding / single device (up
to float reassociation), and must actually eliminate the padded-max tax on
a hub-skewed graph that defeats the vertex partitioner."""

import jax
import numpy as np
import pytest

from roc_tpu.graph import datasets
from roc_tpu.graph.csr import add_self_edges, from_edges
from roc_tpu.graph.partition import edge_block_arrays, partition_graph
from roc_tpu.models import build_gcn, build_sage
from roc_tpu.parallel.check import check_shard_consistency
from roc_tpu.parallel.spmd import SpmdTrainer
from roc_tpu.train.config import Config


def small_ds(seed=5):
    return datasets.synthetic("es", 400, 5.0, 10, 4, n_train=80, n_val=80,
                              n_test=80, seed=seed)


def hub_graph(n=300, hub_deg=150, seed=2):
    """A hub vertex whose in-degree alone exceeds the per-part edge cap —
    the skew case the greedy vertex partitioner cannot balance."""
    rng = np.random.default_rng(seed)
    s = rng.integers(0, n, size=3 * n)
    d = rng.integers(0, n, size=3 * n)
    hub_src = rng.integers(0, n, size=hub_deg)
    s = np.concatenate([s, hub_src])
    d = np.concatenate([d, np.zeros(hub_deg, np.int64)])
    keep = s != d
    return add_self_edges(from_edges(n, s[keep], d[keep]))


def test_edge_blocks_are_exactly_balanced():
    ds = small_ds()
    part = partition_graph(ds.graph, 4)
    src, dst = edge_block_arrays(ds.graph, part.meta)
    P, Eb = src.shape
    assert P * Eb - ds.graph.num_edges < Eb  # <1 block of padding total
    # dst ascending within every block (sorted segment sums)
    assert all(np.all(np.diff(dst[p]) >= 0) for p in range(P))
    # padded ids decode to the original edge list
    S = part.shard_nodes
    own = dst.reshape(-1)[: ds.graph.num_edges]
    back = part.bounds[own // S, 0] + own % S
    np.testing.assert_array_equal(back, ds.graph.dst_idx)


@pytest.mark.parametrize("parts", [2, 4, 8])
def test_edge_shard_matches_single_device_gcn(parts):
    ds = small_ds()
    cfg = Config(layers=[ds.in_dim, 8, ds.num_classes], num_epochs=1,
                 dropout_rate=0.0, num_parts=parts, edge_shard=True,
                 eval_every=10**9)
    check_shard_consistency(cfg, ds, build_gcn(cfg.layers, 0.0))


def test_edge_shard_avg_sage_matches_single_device():
    ds = small_ds(seed=9)
    cfg = Config(layers=[ds.in_dim, 8, ds.num_classes], num_epochs=1,
                 dropout_rate=0.0, num_parts=4, edge_shard=True,
                 eval_every=10**9)
    check_shard_consistency(cfg, ds, build_sage(cfg.layers, 0.0))


def test_edge_shard_trains_and_matches_vertex_shard():
    ds = small_ds(seed=11)
    base = dict(layers=[ds.in_dim, 8, ds.num_classes], num_epochs=5,
                dropout_rate=0.0, num_parts=4, eval_every=10**9)
    tv = SpmdTrainer(Config(**base, halo=True), ds, build_gcn(base["layers"], 0.0))
    te = SpmdTrainer(Config(**base, edge_shard=True), ds,
                     build_gcn(base["layers"], 0.0))
    for _ in range(5):
        tv.run_epoch()
        te.run_epoch()
    mv, me = jax.device_get(tv.evaluate()), jax.device_get(te.evaluate())
    # 5 epochs of accumulated reassociation: counts within 1, loss close
    for f in mv._fields:
        a, b = float(getattr(mv, f)), float(getattr(me, f))
        tol = 2e-3 * max(abs(a), 1.0) if f == "train_loss" else 1.0
        assert abs(a - b) <= tol, (f, a, b)


def test_hub_graph_tax_vertex_vs_edge():
    # hub in-degree (600) >> edge cap (ceil(E/P) ~ 225): the hub's shard is
    # ~3x the mean and every other shard pads up to it
    g = hub_graph(hub_deg=600)
    part = partition_graph(g, 8)
    live = part.num_edges_valid.astype(float)
    vertex_tax = part.shard_edges * part.num_parts / live.sum() - 1.0
    src, dst = edge_block_arrays(g, part.meta)
    edge_tax = src.size / g.num_edges - 1.0
    # the hub makes vertex sharding pay heavily; edge blocks stay tight
    assert vertex_tax > 0.30
    assert edge_tax < 0.05


def test_edge_shard_auto_selection():
    """-edge-shard defaults to "auto": hub-skewed partitions flip to edge
    sharding (padded-max tax > threshold, docs/PERF.md rule of thumb);
    uniform graphs stay on vertex sharding; GAT never auto-enables."""
    from roc_tpu.models import build_gat

    g = hub_graph(hub_deg=2000)   # hub in-degree >> per-part edge cap
    lab = np.zeros(g.num_nodes, np.int64)
    hub_ds = datasets.Dataset(
        name="hub", graph=g,
        features=np.random.default_rng(0).normal(
            size=(g.num_nodes, 10)).astype(np.float32),
        labels=None, label_ids=lab,
        mask=np.zeros(g.num_nodes, np.int32), in_dim=10, num_classes=4)
    base = dict(layers=[10, 8, 4], num_epochs=1, dropout_rate=0.0,
                eval_every=10 ** 9, num_parts=4)
    t = SpmdTrainer(Config(**base), hub_ds, build_gcn(base["layers"], 0.0))
    assert t._use_edge_shard and t.gdata.mode == "edge"

    uni = small_ds()
    t2 = SpmdTrainer(Config(**base), uni, build_gcn(base["layers"], 0.0))
    assert not t2._use_edge_shard and t2.gdata.mode != "edge"

    # explicit off overrides even on the hub graph
    t3 = SpmdTrainer(Config(**base, edge_shard="off"), hub_ds,
                     build_gcn(base["layers"], 0.0))
    assert not t3._use_edge_shard

    # GAT on the XLA attention backend must not auto-enable (_edge_attend's
    # autodiff backward scatters serialize on TPU — correctness path only)
    t4 = SpmdTrainer(Config(**base, model="gat"), hub_ds,
                     build_gat(base["layers"], 0.0))
    assert not t4._use_edge_shard
    # ...but on the PLAN backend (scatter-free edge_gat_attend, round 4)
    # the same hub graph auto-enables
    t5 = SpmdTrainer(Config(**base, model="gat",
                            aggregate_backend="matmul"), hub_ds,
                     build_gat(base["layers"], 0.0))
    assert t5._use_edge_shard and t5.gdata.mode == "edge"
    assert t5.gdata.gat_plans is not None


@pytest.mark.parametrize("model_builder,kwargs",
                         [(build_gcn, {}), (build_sage, {})])
def test_edge_shard_matmul_backend_matches_xla(model_builder, kwargs):
    """-edge-shard -aggr-backend matmul (the TPU-scale path: per-block
    one-hot plans into the padded-global space instead of the serialized
    scatter) must train identically to the xla edge path and to the
    single-device reference."""
    ds = small_ds(seed=21)
    base = dict(layers=[ds.in_dim, 8, ds.num_classes], num_epochs=1,
                dropout_rate=0.0, num_parts=4, edge_shard=True,
                eval_every=10**9, seed=3)
    cfg_mm = Config(**base, aggregate_backend="matmul")
    # plan construction happened and the backend stuck
    t_mm = SpmdTrainer(cfg_mm, ds, model_builder(base["layers"], 0.0,
                                                 **kwargs))
    assert t_mm.gdata.backend == "matmul" and t_mm.gdata.mode == "edge"
    assert t_mm.gdata.plans is not None
    # exact single-device consistency (fp32 one-hot dots are exact)
    check_shard_consistency(cfg_mm, ds, model_builder(base["layers"], 0.0,
                                                      **kwargs),
                            sharded_trainer=t_mm)
    # loss trajectory tracks the xla edge path
    t_x = SpmdTrainer(Config(**base, aggregate_backend="xla"), ds,
                      model_builder(base["layers"], 0.0, **kwargs))
    for _ in range(3):
        lm = float(t_mm.run_epoch())
        lx = float(t_x.run_epoch())
    assert abs(lm - lx) < 1e-3 * max(abs(lx), 1.0), (lm, lx)


def test_edge_shard_binned_request_degrades_to_matmul(capsys, monkeypatch):
    """An explicit -aggr-backend binned with -edge-shard on a graph whose
    block windows fail the binned occupancy bound must print the note and
    fall back to the matmul windowed plans.  (The bound is monkeypatched
    shut: small test graphs have tiny block windows, which the real bound
    happily accepts.)"""
    from roc_tpu.ops.pallas import binned as B
    monkeypatch.setattr(B, "binned_viable", lambda *a: False)
    ds = small_ds(seed=23)
    cfg = Config(layers=[ds.in_dim, 8, ds.num_classes], num_epochs=1,
                 dropout_rate=0.0, num_parts=4, edge_shard=True,
                 eval_every=10**9, aggregate_backend="binned")
    t = SpmdTrainer(cfg, ds, build_gcn(cfg.layers, 0.0))
    assert t.gdata.backend == "matmul"
    assert "occupancy bound; using matmul" in capsys.readouterr().err
    assert np.isfinite(float(t.run_epoch()))


def test_edge_shard_binned_matches_xla(monkeypatch):
    """-edge-shard -aggr-backend binned (block-windowed binned kernels,
    VERDICT r2 composition gap): losses must track the xla edge path.
    The occupancy bound is monkeypatched open — the test graph is far too
    small to pass it naturally."""
    from roc_tpu.ops.pallas import binned as B
    from roc_tpu.parallel.spmd import EdgeBinnedPlans
    monkeypatch.setattr(B, "binned_viable", lambda *a: True)
    ds = small_ds(seed=29)
    layers = [ds.in_dim, 8, ds.num_classes]

    def make(backend):
        cfg = Config(layers=layers, num_epochs=3, dropout_rate=0.0,
                     num_parts=4, edge_shard=True, eval_every=10**9,
                     aggregate_backend=backend)
        return SpmdTrainer(cfg, ds, build_gcn(layers, 0.0))

    t_b, t_x = make("binned"), make("xla")
    assert t_b.gdata.backend == "binned"
    assert isinstance(t_b.gdata.plans, EdgeBinnedPlans)
    for i in range(3):
        lb, lx = float(t_b.run_epoch()), float(t_x.run_epoch())
        np.testing.assert_allclose(lb, lx, rtol=2e-3, err_msg=f"epoch {i}")


def test_edge_plans_are_windowed():
    """Plan size per block must scale with the block's own window span
    (~NS/P for uniform graphs), not with the full P*S table — the property
    that keeps edge-shard matmul viable at pod scale (empty-window chunks
    would otherwise floor every block's plan at NS/VB chunks)."""
    from roc_tpu.graph.partition import compute_meta
    from roc_tpu.ops.pallas.segment_sum import VB
    from roc_tpu.parallel.spmd import build_edge_plans

    ds = datasets.synthetic("wintest", 4000, 8.0, 8, 3, n_train=100,
                            n_val=100, n_test=100, seed=3)
    meta = compute_meta(ds.graph.row_ptr, 8)
    ep = build_edge_plans(ds.graph, meta)
    NS = meta.num_parts * meta.shard_nodes
    naive_floor = NS // VB
    for side in ("fwd", "bwd"):
        C = getattr(ep, f"{side}_obi").shape[1]
        span = getattr(ep, f"span_{side}")
        assert C < naive_floor // 2, (side, C, naive_floor)
        # span ~ one shard's stripe (+ block-boundary spill), far below NS
        assert span <= NS // meta.num_parts + 2 * VB + 64, (side, span)
        # window bases + span stay inside the NS-row accumulator exactly
        # (a clamped dynamic_update_slice would silently shift sums)
        bases = np.asarray(getattr(ep, f"{side}_base"))
        assert bases.min() >= 0
        assert bases.max() + span <= NS


def test_edge_shard_matmul_bf16_smoke():
    """bf16 activations through the edge-mode custom vjp (all_gather +
    windowed one-hot dots + psum_scatter must all keep bf16 happy)."""
    ds = small_ds(seed=31)
    cfg = Config(layers=[ds.in_dim, 8, ds.num_classes], num_epochs=2,
                 dropout_rate=0.0, num_parts=4, edge_shard=True,
                 eval_every=10**9, aggregate_backend="matmul",
                 use_bf16=True, seed=3)
    t = SpmdTrainer(cfg, ds, build_gcn(cfg.layers, 0.0))
    assert t.gdata.backend == "matmul" and t.gdata.plans is not None
    for _ in range(2):
        loss = t.run_epoch()
    assert np.isfinite(float(loss))
