"""The reference's built-in GCN program (top_level_task, gnn.cc:75-92).

Per hidden layer i = 1..L-1:
    t = dropout(t, rate)
    input = t
    t = linear(t, layers[i])            # no fused activation in the recipe
    t = indegree_norm(t)
    t = scatter_gather(t)               # sum over in-edges
    t = indegree_norm(t)                # → symmetric D^-1/2 A D^-1/2
    if not last: t = relu(t)
    if len(layers) > 3:                 # residual path for deep GCNs
        input = linear(input, t.dim)    # always projected, gnn.cc:87-88
        t = add(t, input)
final: softmax_cross_entropy(t, label, mask)
"""

from __future__ import annotations

from typing import Sequence

from roc_tpu.models.model import Model


def build_gcn(layers: Sequence[int], dropout_rate: float = 0.5,
              aggr: str = "sum", residual: bool = True) -> Model:
    """layers = [in_dim, hidden..., num_classes] — the CLI's `-layers` spec.

    ``residual=False`` builds the reference's shallow-GCN recipe at any
    depth (no projected skip path).  The deep-GCN residual ``add``
    consumes each layer's boundary tensor alongside the projection, so it
    pins that boundary in HBM and stops the round-16 fusion-region
    planner at every layer — a residual-free stack is the norm-folded
    chain ``mega_regions`` can fuse end to end.
    """
    assert len(layers) >= 2
    model = Model(in_dim=layers[0])
    t = model.input
    for i in range(1, len(layers)):
        t = model.dropout(t, dropout_rate)
        residual_in = t
        t = model.linear(t, layers[i])
        t = model.indegree_norm(t)
        t = model.scatter_gather(t, aggr)
        t = model.indegree_norm(t)
        if i != len(layers) - 1:
            t = model.relu(t)
        if residual and len(layers) > 3:
            proj = model.linear(residual_in, t.dim)
            t = model.add(t, proj)
        model.end_layer()
    model.softmax_cross_entropy(t)
    return model
