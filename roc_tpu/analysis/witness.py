"""Runtime lock-order witness: the dynamic half of roc-threads.

The static analyzer (:mod:`roc_tpu.analysis.threads`) derives the
sanctioned lock-order graph from the AST; this module checks that graph
against *reality*.  Every sanctioned lock site wraps its primitive in
``trace(name, lock)``:

* **Disarmed** (the default): ``trace`` returns the primitive untouched —
  the serving hot path pays literally zero cost, not even an attribute
  indirection.  Arming is decided once, at lock *creation* time.
* **Armed** (``ROC_OBS=1`` / ``ROC_WITNESS=1`` in the environment, or an
  explicit :func:`arm` before the locks are created — the tier-1
  threaded suites do the latter): ``trace`` returns a proxy that keeps a
  thread-local stack of held witness names and records every *new*
  (outer, inner) acquisition pair, both in-process (for
  :func:`validate`) and as a ``lock_order`` event on the shared
  telemetry JSONL via ``fault.emit_event`` (best-effort: dropped when no
  obs sink is attached, like every other fault event).

``validate(edges)`` asserts every observed pair is inside the static
graph (transitive closure — holding A while taking C is fine when the
graph sanctions A→B→C).  Re-entrant same-name acquisitions (RLock) are
never recorded: they order nothing.  ``Condition.wait`` releases and
reacquires its lock, so the proxy drops the name for the duration of the
wait and re-records the reacquisition — a wait that comes back while the
thread holds other locks is a real ordering event and is witnessed as
one.

The witness deliberately wraps only the *sanctioned* sites the analyzer
names (serve queue CV, plan lock, delta mutation lock, prefetch-ring
lock, in-proc transport CV).  The fault/retry leaf locks stay raw: the
proxy itself emits through ``fault``, and witnessing the emitter's own
lock would recurse.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["arm", "armed", "trace", "observed_pairs", "records",
           "reset", "validate"]

_ARMED = os.environ.get("ROC_OBS", "") == "1" \
    or os.environ.get("ROC_WITNESS", "") == "1"

_TLS = threading.local()
_MU = threading.Lock()                       # guards the two tables below
_PAIRS: Dict[Tuple[str, str], int] = {}      # (outer, inner) -> count
_EMITTED: Set[Tuple[str, str]] = set()       # pairs already on the JSONL


def arm(on: bool = True) -> None:
    """Arm/disarm the witness for locks created *after* this call.
    Locks already handed out keep whatever they were born as — a raw
    primitive stays raw, a proxy keeps witnessing (its records are
    simply ignored by a later reset())."""
    global _ARMED
    _ARMED = bool(on)


def armed() -> bool:
    return _ARMED


def trace(name: str, lock):
    """Wrap ``lock`` under the static graph's node name (``Class.attr``).
    Returns ``lock`` itself when disarmed — zero overhead — else a
    recording proxy.  The analyzer cross-checks ``name`` against the
    attribute the result is assigned to (rule ``witness-name``)."""
    if not _ARMED:
        return lock
    return _WitnessLock(name, lock)


def _held() -> List[str]:
    h = getattr(_TLS, "held", None)
    if h is None:
        h = _TLS.held = []
    return h


def _record_entry(name: str) -> None:
    held = _held()
    fresh = name not in held
    held.append(name)
    if not fresh:
        return                       # re-entrant (RLock): orders nothing
    new_pairs = []
    with _MU:
        # dict.fromkeys: a re-entrantly held outer appears once per
        # depth on the stack but orders against `name` exactly once
        for outer in dict.fromkeys(held[:-1]):
            if outer == name:
                continue
            key = (outer, name)
            n = _PAIRS.get(key, 0)
            _PAIRS[key] = n + 1
            if key not in _EMITTED:
                _EMITTED.add(key)
                new_pairs.append(key)
    for outer, inner in new_pairs:
        # best-effort JSONL record; import here keeps this module
        # import-light and breaks no cycle when fault pulls analysis in
        from roc_tpu import fault
        fault.emit_event("lock_order", outer=outer, inner=inner)


def _record_exit(name: str) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            return


class _WitnessLock:
    """Delegating proxy over Lock/RLock/Condition.  Only the methods the
    tree actually uses are wrapped; everything else falls through."""

    def __init__(self, name: str, lock):
        self._name = name
        self._lock = lock

    # -- context manager / lock face -----------------------------------
    def __enter__(self):
        out = self._lock.__enter__()
        _record_entry(self._name)
        return out

    def __exit__(self, *exc):
        _record_exit(self._name)
        return self._lock.__exit__(*exc)

    def acquire(self, *a, **kw):
        got = self._lock.acquire(*a, **kw)
        if got:
            _record_entry(self._name)
        return got

    def release(self):
        _record_exit(self._name)
        return self._lock.release()

    def locked(self):
        return self._lock.locked()

    # -- condition face -------------------------------------------------
    def wait(self, timeout: Optional[float] = None):
        # wait() releases the underlying lock for its duration; the
        # reacquisition on wake is a real ordering event vs. anything
        # else this thread still holds, so drop + re-record.
        _record_exit(self._name)
        try:
            return self._lock.wait(timeout)
        finally:
            _record_entry(self._name)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        _record_exit(self._name)
        try:
            return self._lock.wait_for(predicate, timeout)
        finally:
            _record_entry(self._name)

    def notify(self, n: int = 1):
        return self._lock.notify(n)

    def notify_all(self):
        return self._lock.notify_all()

    def __repr__(self):
        return f"<witness {self._name!r} over {self._lock!r}>"


# -- inspection / validation ------------------------------------------------

def observed_pairs() -> Dict[Tuple[str, str], int]:
    with _MU:
        return dict(_PAIRS)


def records() -> int:
    """Total distinct pairs recorded since the last reset (the number of
    ``lock_order`` events that reached — or would have reached — the
    telemetry JSONL)."""
    with _MU:
        return len(_PAIRS)


def reset() -> None:
    with _MU:
        _PAIRS.clear()
        _EMITTED.clear()


def _closure(edges) -> Set[Tuple[str, str]]:
    adj: Dict[str, Set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
    out: Set[Tuple[str, str]] = set()
    for a in list(adj):
        seen: Set[str] = set()
        stack = list(adj.get(a, ()))
        while stack:
            b = stack.pop()
            if b in seen:
                continue
            seen.add(b)
            out.add((a, b))
            stack.extend(adj.get(b, ()))
    return out

def validate(edges=None) -> List[str]:
    """Every observed (outer, inner) pair must sit inside the sanctioned
    lock-order graph.  ``edges`` defaults to the committed
    ``threads.json`` baseline; returns human-readable violations (empty
    = the runtime agreed with the static graph)."""
    if edges is None:
        from roc_tpu.analysis import threads as _threads
        edges = _threads.load_baseline()["edges"]
    allowed = _closure(tuple(e) for e in edges)
    out = []
    for (a, b), n in sorted(observed_pairs().items()):
        if (a, b) not in allowed:
            out.append(f"observed {a} -> {b} ({n}x) is not an edge of "
                       f"the static lock-order graph")
    return out
