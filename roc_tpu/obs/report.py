"""Render a -obs run's trace + metrics into a text summary, plus the
preflight selftest.

`python -m roc_tpu.obs report -dir roc_obs` reads the two artifacts a
`-obs` run writes (trace.json, metrics.jsonl) and prints per-span-type
aggregates, the epoch/loss trajectory, and any watchdog alerts — the
10-second answer to "where did this run spend its time" without opening
Perfetto.  `selftest` is the preflight/CI gate: tracer schema validity,
watchdog fire/quiet behavior, and the span overhead bound, all stdlib-only
(no jax import) so it runs in ~100 ms.
"""

from __future__ import annotations

import json
from typing import List

from roc_tpu.obs.metrics import load_jsonl
from roc_tpu.obs.tracer import SpanTracer, validate_chrome_trace
from roc_tpu.obs.watchdog import PerfWatchdog

# Gates for the selftest's overhead check.  A disabled span is two
# perf_counter_ns calls + a list push/pop; an enabled one adds a ring
# append.  50 us/span is ~100x the measured cost — the gate catches a
# pathological regression (lock contention, accidental I/O), not jitter.
MAX_SPAN_OVERHEAD_S = 50e-6


def summarize_trace(trace: dict) -> List[str]:
    by_name: dict = {}
    for ev in trace.get("traceEvents", []):
        st = by_name.setdefault(ev.get("name", "?"),
                                {"count": 0, "total_us": 0.0, "max_us": 0.0})
        st["count"] += 1
        dur = float(ev.get("dur", 0.0))
        st["total_us"] += dur
        st["max_us"] = max(st["max_us"], dur)
    lines = [f"# spans ({len(by_name)} types)"]
    for name, st in sorted(by_name.items(), key=lambda kv: -kv[1]["total_us"]):
        mean = st["total_us"] / st["count"]
        lines.append(f"#   {name:<16} x{st['count']:<5} "
                     f"total {st['total_us'] / 1e3:9.2f} ms  "
                     f"mean {mean / 1e3:8.3f} ms  "
                     f"max {st['max_us'] / 1e3:8.3f} ms")
    return lines


def summarize_metrics(records: List[dict]) -> List[str]:
    epochs = [r for r in records if r.get("type") == "metrics"]
    alerts = [r for r in records if r.get("type") == "watchdog"]
    trains = [r for r in records if r.get("type") == "train"]
    lines: List[str] = []
    if epochs:
        walls = [r["wall_s"] for r in epochs if "wall_s" in r]
        med = sorted(walls)[len(walls) // 2] if walls else 0.0
        lines.append(f"# metrics: {len(epochs)} epochs, "
                     f"median {med * 1e3:.1f} ms/epoch")
        last = epochs[-1]
        for key in ("loss", "grad_norm", "param_norm", "wire_bytes"):
            if key in last:
                lines.append(f"#   final {key} = {last[key]:.6g}")
    for r in trains:
        lines.append(f"#   verdict: {r.get('watchdog_verdict', '?')} "
                     f"({r.get('epochs', '?')} epochs, "
                     f"total {r.get('total_s', 0):.2f}s)")
    if alerts:
        lines.append(f"# watchdog alerts ({len(alerts)}):")
        for a in alerts:
            if a.get("kind") == "straggler":
                lines.append(f"#   straggler part {a.get('part')} @ epoch "
                             f"{a.get('epoch')}: {a.get('ratio', 0):.2f}x "
                             f"the shard median")
            else:
                lines.append(f"#   slow epoch {a.get('epoch')}: "
                             f"{a.get('wall_s', 0) * 1e3:.1f} ms = "
                             f"{a.get('ratio', 0):.2f}x the EWMA")
    elif epochs or trains:
        lines.append("# watchdog: no alerts")
    return lines


def report(trace_path: str = "", metrics_path: str = "") -> str:
    lines: List[str] = []
    if trace_path:
        try:
            with open(trace_path, encoding="utf-8") as f:
                trace = json.load(f)
        except (OSError, ValueError) as e:
            lines.append(f"# trace: unreadable ({e})")
        else:
            problems = validate_chrome_trace(trace)
            if problems:
                lines.append(f"# trace: {len(problems)} schema problem(s): "
                             f"{problems[0]}")
            lines.extend(summarize_trace(trace))
    if metrics_path:
        records = load_jsonl(metrics_path)
        if records:
            lines.extend(summarize_metrics(records))
        else:
            lines.append(f"# metrics: no records at {metrics_path}")
    return "\n".join(lines) if lines else "# nothing to report"


# -- selftest (the preflight obs gate) -------------------------------------

def selftest(out=print) -> int:
    """0 when the obs layer holds its own contracts; 1 with a reason."""
    failures: List[str] = []

    # 1. tracer: nesting depths + Perfetto-loadable export
    tr = SpanTracer(capacity=64)
    tr.enabled = True
    with tr.span("outer", case="selftest"):
        with tr.span("inner"):
            pass
    spans = {s.name: s for s in tr.spans()}
    if set(spans) != {"outer", "inner"}:
        failures.append(f"tracer recorded {sorted(spans)}, "
                        "expected inner+outer")
    elif not (spans["inner"].depth == 1 and spans["outer"].depth == 0):
        failures.append("span nesting depths wrong")
    problems = validate_chrome_trace(tr.to_chrome_trace())
    if problems:
        failures.append(f"chrome-trace schema: {problems[0]}")
    try:
        json.dumps(tr.to_chrome_trace())
    except TypeError as e:
        failures.append(f"trace not JSON-serializable: {e}")

    # 2. watchdog: fires on an injected 3x epoch, quiet on a clean run
    wd = PerfWatchdog()
    for epoch in range(5):
        if wd.observe_epoch(epoch, 0.1) is not None:
            failures.append("watchdog fired on a clean warmup")
            break
    if wd.observe_epoch(5, 0.3) is None:
        failures.append("watchdog missed an injected 3x slow epoch")
    clean = PerfWatchdog()
    noise = [0.1, 0.102, 0.098, 0.101, 0.099, 0.103, 0.097]
    if any(clean.observe_epoch(i, t) for i, t in enumerate(noise)):
        failures.append("watchdog fired on +-3% noise")
    if not clean.observe_shards(0, [0.1, 0.1, 0.1, 0.5]):
        failures.append("watchdog missed a 5x shard straggler")

    # 3. overhead: disabled spans (the always-on steady state) stay cheap
    tr2 = SpanTracer()
    reps = 2000
    with tr2.span("gate") as gate:   # obs times itself — no raw clocks
        for _ in range(reps):
            with tr2.span("probe"):
                pass
    per_span = gate.dur_s / reps
    if per_span > MAX_SPAN_OVERHEAD_S:
        failures.append(f"span overhead {per_span * 1e6:.1f} us > "
                        f"{MAX_SPAN_OVERHEAD_S * 1e6:.0f} us")

    if failures:
        for f_ in failures:
            out(f"obs selftest FAIL: {f_}")
        return 1
    out(f"obs selftest ok (span overhead {per_span * 1e6:.2f} us, "
        f"watchdog fire/quiet verified, trace schema valid)")
    return 0
