"""Host-side CSR graph core (NumPy).

The reference stores the graph as an in-edge CSR: for each destination vertex
``v`` the row range holds the *source* vertex ids of v's in-edges
(reference: load_task.cu:271-294 builds ``EdgeStruct{src,dst}`` with
``dst = row vertex``; gnn.cc:790-793 creates rowPtr over vertices and colIdx
over edges).  We keep the same orientation: ``col_idx[row_ptr[v]:row_ptr[v+1]]``
are the sources of v's in-edges.

Differences from the reference, by design:
  * row_ptr is the standard exclusive-prefix form of length N+1 (the `.lux`
    on-disk form — inclusive end offsets of length N — is converted at the IO
    boundary, see roc_tpu/graph/lux.py).
  * Everything here is plain NumPy on the host; device-side representations
    (padded shards) are produced by roc_tpu/graph/partition.py.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Reference typedefs (types.h:5-7): V_ID=uint32, E_ID=uint64.  We use int32 /
# int64 because XLA gathers want signed indices; the on-disk format keeps the
# unsigned types.
V_DTYPE = np.int32
E_DTYPE = np.int64


@dataclasses.dataclass(frozen=True)
class Csr:
    """In-edge CSR: col_idx[row_ptr[v]:row_ptr[v+1]] = sources of v's in-edges."""

    num_nodes: int
    num_edges: int
    row_ptr: np.ndarray  # [N+1] E_DTYPE, exclusive prefix, row_ptr[0]==0
    col_idx: np.ndarray  # [E]   V_DTYPE, source vertex per edge

    def __post_init__(self):
        assert self.row_ptr.shape == (self.num_nodes + 1,)
        assert self.col_idx.shape == (self.num_edges,)
        assert self.row_ptr[0] == 0
        assert self.row_ptr[-1] == self.num_edges

    def validate(self) -> None:
        # Mirrors the reference's load-time asserts (gnn.cc:797-800): row
        # offsets monotone, final offset == numEdges, sources in range.
        assert np.all(np.diff(self.row_ptr) >= 0), "row_ptr not monotone"
        if self.num_edges:
            assert self.col_idx.min() >= 0
            assert self.col_idx.max() < self.num_nodes

    @property
    def in_degrees(self) -> np.ndarray:
        """Per-vertex in-degree (the quantity InDegreeNorm divides by,
        graphnorm_kernel.cu:19-57 computes it from row_ptr diffs)."""
        return np.diff(self.row_ptr).astype(E_DTYPE)

    @property
    def dst_idx(self) -> np.ndarray:
        """Per-edge destination vertex (expanded from row_ptr), sorted ascending."""
        return np.repeat(
            np.arange(self.num_nodes, dtype=V_DTYPE), np.diff(self.row_ptr)
        )

    def transpose(self) -> "Csr":
        """Out-edge view as a CSR over sources (used by aggregation backward:
        the reference reuses the same kernel with roles swapped,
        scattergather_kernel.cu:160-170).  Big graphs take the native
        O(E) counting sort (roc_csr_transpose — stable, so element-equal
        to this NumPy stable-argsort oracle; ~30-60 s -> seconds at
        products scale, on the reorder and .t.lux preprocessing paths)."""
        from roc_tpu import native
        if self.num_edges >= (1 << 20) and native.available():
            # range-check first: the NumPy path fails loudly on corrupt
            # ids (bincount/cumsum raise); the C counting sort would
            # index out of bounds instead
            if int(self.col_idx.min()) < 0 or \
                    int(self.col_idx.max()) >= self.num_nodes:
                raise ValueError("col_idx out of range [0, num_nodes)")
            t_row, t_col = native.csr_transpose(self.row_ptr, self.col_idx)
            return Csr(self.num_nodes, self.num_edges,
                       t_row.astype(E_DTYPE, copy=False),
                       t_col.astype(V_DTYPE, copy=False))
        order = np.argsort(self.col_idx, kind="stable")
        new_col = self.dst_idx[order].astype(V_DTYPE)
        counts = np.bincount(self.col_idx, minlength=self.num_nodes)
        new_row = np.zeros(self.num_nodes + 1, dtype=E_DTYPE)
        np.cumsum(counts, out=new_row[1:])
        return Csr(self.num_nodes, self.num_edges, new_row, new_col)


def from_edges(num_nodes: int, src: np.ndarray, dst: np.ndarray) -> Csr:
    """Build an in-edge CSR from an edge list (dedup is the caller's job)."""
    src = np.asarray(src, dtype=V_DTYPE)
    dst = np.asarray(dst, dtype=V_DTYPE)
    assert src.shape == dst.shape
    order = np.argsort(dst, kind="stable")
    col_idx = src[order]
    counts = np.bincount(dst, minlength=num_nodes)
    row_ptr = np.zeros(num_nodes + 1, dtype=E_DTYPE)
    np.cumsum(counts, out=row_ptr[1:])
    return Csr(num_nodes, int(src.shape[0]), row_ptr, col_idx)


def with_edge_delta(g: Csr, add: np.ndarray = None,
                    retire: np.ndarray = None) -> Csr:
    """Rebuild-from-scratch oracle for dynamic deltas (tests + the
    serving replan path, roc_tpu/serve/delta.py): apply an [n, 2]
    (src, dst) add list and a retire list to ``g`` and rebuild through
    :func:`from_edges`.  Retires remove the LAST live instance of each
    (src, dst) pair — the same most-recently-added-first rule the
    incremental patchers use — so the oracle and the patched plans
    describe the same multiset.  Raises KeyError on retiring an edge
    with no live instance (the caller classifies no-ops)."""
    src = g.col_idx.astype(np.int64).tolist()
    dst = g.dst_idx.astype(np.int64).tolist()
    alive = [True] * len(src)
    refs: dict = {}
    for gi, sd in enumerate(zip(src, dst)):
        refs.setdefault(sd, []).append(gi)
    if add is not None:
        for s, d in np.asarray(add, np.int64).reshape(-1, 2).tolist():
            refs.setdefault((s, d), []).append(len(src))
            src.append(s)
            dst.append(d)
            alive.append(True)
    if retire is not None:
        for s, d in np.asarray(retire, np.int64).reshape(-1, 2).tolist():
            stack = refs.get((s, d))
            if not stack:
                raise KeyError(f"retire of dead edge ({s}, {d})")
            alive[stack.pop()] = False
    live_s = np.asarray([s for s, a in zip(src, alive) if a], V_DTYPE)
    live_d = np.asarray([d for d, a in zip(dst, alive) if a], V_DTYPE)
    return from_edges(g.num_nodes, live_s, live_d)


def add_self_edges(g: Csr) -> Csr:
    """Add one self-edge per vertex if not already present.

    The reference consumes pre-processed ``<file>.add_self_edge.lux`` inputs
    (gnn.cc:755); this is the converter that produces that graph from a raw
    one.  Idempotent for graphs that already have all self-edges.
    """
    src = g.col_idx
    dst = g.dst_idx
    has_self = np.zeros(g.num_nodes, dtype=bool)
    has_self[src[src == dst]] = True
    missing = np.nonzero(~has_self)[0].astype(V_DTYPE)
    if missing.size == 0:
        return g
    return from_edges(
        g.num_nodes,
        np.concatenate([src, missing]),
        np.concatenate([dst, missing]),
    )
