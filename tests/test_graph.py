"""Graph core tests: CSR, .lux roundtrip, partitioner, padding."""

import numpy as np
import pytest

from roc_tpu.graph import datasets, lux
from roc_tpu.graph.csr import Csr, add_self_edges, from_edges
from roc_tpu.graph.partition import edge_balanced_bounds, partition_graph


def tiny_graph():
    # 5 vertices; in-edges (dst <- src): 0<-1, 0<-2, 1<-0, 2<-3, 3<-4, 4<-0
    src = [1, 2, 0, 3, 4, 0]
    dst = [0, 0, 1, 2, 3, 4]
    return from_edges(5, src, dst)


def test_from_edges_builds_in_edge_csr():
    g = tiny_graph()
    assert g.num_edges == 6
    assert list(np.diff(g.row_ptr)) == [2, 1, 1, 1, 1]
    assert sorted(g.col_idx[:2].tolist()) == [1, 2]  # sources of v0's in-edges
    assert g.col_idx[2] == 0
    g.validate()


def test_add_self_edges_idempotent():
    g = add_self_edges(tiny_graph())
    assert g.num_edges == 6 + 5
    assert np.all(np.diff(g.row_ptr) == [3, 2, 2, 2, 2])
    g2 = add_self_edges(g)
    assert g2.num_edges == g.num_edges


def test_transpose_roundtrip():
    g = tiny_graph()
    t = g.transpose().transpose()
    assert np.array_equal(t.row_ptr, g.row_ptr)
    # within-row order may differ; compare per-row sorted sources
    for v in range(g.num_nodes):
        a = np.sort(g.col_idx[g.row_ptr[v]:g.row_ptr[v + 1]])
        b = np.sort(t.col_idx[t.row_ptr[v]:t.row_ptr[v + 1]])
        assert np.array_equal(a, b)


def test_lux_roundtrip(tmp_path):
    g = add_self_edges(tiny_graph())
    path = str(tmp_path / "tiny") + lux.LUX_SUFFIX
    lux.write_lux(path, g)
    g2 = lux.read_lux(path)
    assert g2.num_nodes == g.num_nodes
    assert g2.num_edges == g.num_edges
    assert np.array_equal(g2.row_ptr, g.row_ptr)
    assert np.array_equal(g2.col_idx, g.col_idx)
    # header layout byte-check: uint32 + uint64 + N*uint64 + E*uint32
    raw = open(path, "rb").read()
    assert len(raw) == 4 + 8 + 8 * g.num_nodes + 4 * g.num_edges


def test_dataset_files_roundtrip(tmp_path):
    ds = datasets.synthetic("t", 40, 3.0, 6, 3, n_train=10, n_val=10,
                            n_test=10, seed=7)
    prefix = str(tmp_path / "t")
    lux.write_dataset(prefix, ds.graph, ds.features, ds.label_ids, ds.mask)
    ds2 = datasets.load_roc_dataset(prefix, ds.in_dim, ds.num_classes)
    assert np.array_equal(ds2.graph.col_idx, ds.graph.col_idx)
    np.testing.assert_allclose(ds2.features, ds.features, rtol=1e-5)
    assert np.array_equal(ds2.label_ids, ds.label_ids)
    assert np.array_equal(ds2.mask, ds.mask)
    # second load hits the .feats.bin cache path (load_task.cu:41-73 behavior)
    assert (tmp_path / "t.feats.bin").exists()
    ds3 = datasets.load_roc_dataset(prefix, ds.in_dim, ds.num_classes)
    np.testing.assert_allclose(ds3.features, ds.features, rtol=1e-5)
    # A consumer that lost the .bin sidecar and reparses the CSV must get
    # BIT-identical features to the cache-hit load (the CSV is written at
    # %.9g = exact float32 round-trip) — runs on "the same dataset" may
    # never diverge based on which file happened to be read.
    cached = ds3.features.copy()
    (tmp_path / "t.feats.bin").unlink()
    ds4 = datasets.load_roc_dataset(prefix, ds.in_dim, ds.num_classes)
    assert np.array_equal(ds4.features, cached)


def test_edge_balanced_bounds_matches_reference_rule():
    # Mirror gnn.cc:806-829 by hand on a known degree sequence.
    g = add_self_edges(tiny_graph())  # degrees [3,2,2,2,2], E=11
    bounds = edge_balanced_bounds(g, 2)  # cap = ceil(11/2) = 6
    # cnt: 3,5,7>6 -> cut at v=2; remainder (3,4)
    assert bounds == [(0, 2), (3, 4)]
    # exact cover, no overlap
    assert bounds[0][1] + 1 == bounds[1][0]


def test_bounds_repair_excess_parts():
    g = add_self_edges(tiny_graph())
    bounds = edge_balanced_bounds(g, 5)  # one vertex each, roughly
    assert len(bounds) == 5
    covered = sorted(v for lo, hi in bounds for v in range(lo, hi + 1))
    assert covered == list(range(5))


@pytest.mark.parametrize("parts", [1, 2, 4])
def test_partition_padding_invariants(parts):
    ds = datasets.synthetic("t", 100, 4.0, 8, 4, n_train=20, n_val=20,
                            n_test=20, seed=3)
    g = ds.graph
    part = partition_graph(g, parts)
    assert part.num_parts == parts
    assert part.shard_nodes % 8 == 0
    # every shard has at least one pad row (zero-source row for pad edges)
    assert np.all(part.num_valid < part.shard_nodes)
    assert part.num_valid.sum() == g.num_nodes
    assert part.num_edges_valid.sum() == g.num_edges
    # pad_nodes/unpad_nodes roundtrip
    x = np.arange(g.num_nodes * 3, dtype=np.float32).reshape(g.num_nodes, 3)
    assert np.array_equal(part.unpad_nodes(part.pad_nodes(x)), x)
    # to_padded agrees with pad layout
    v = np.arange(g.num_nodes)
    pid = part.to_padded(v)
    padded = part.pad_nodes(v.astype(np.float64), fill=-1)
    assert np.array_equal(padded[pid], v.astype(np.float64))
    # edge_dst stays ascending (segment_sum is told indices_are_sorted)
    assert np.all(np.diff(part.edge_dst, axis=1) >= 0)
    # edge arrays reproduce the aggregation: out[dst] = sum input[src]
    feats = np.random.default_rng(0).normal(size=(g.num_nodes, 5)).astype(np.float32)
    xp = part.pad_nodes(feats).reshape(parts * part.shard_nodes, 5)
    out = np.zeros((parts, part.shard_nodes, 5), dtype=np.float32)
    for p in range(parts):
        np.add.at(out[p], part.edge_dst[p], xp[part.edge_src[p]])
    dense = np.zeros_like(feats)
    np.add.at(dense, g.dst_idx, feats[g.col_idx])
    np.testing.assert_allclose(part.unpad_nodes(out.reshape(-1, 5)), dense,
                               rtol=1e-5, atol=1e-5)


def test_partition_degree_and_mask():
    ds = datasets.synthetic("t", 50, 3.0, 4, 3, n_train=10, n_val=10,
                            n_test=10, seed=5)
    part = partition_graph(ds.graph, 4)
    deg = part.in_degree
    assert np.all(deg[~part.node_mask] == 1.0)
    dense_deg = np.diff(ds.graph.row_ptr).astype(np.float32)
    np.testing.assert_array_equal(
        part.unpad_nodes(deg.reshape(-1)), dense_deg)
